"""Netlink-batched ipset writer: coalesced kernel-edge ban inserts.

The subprocess shim in effectors/ipset.py forks `ipset add` once per
ban — fine at reference rates, a bottleneck when the TPU matcher emits
ban bursts.  This module talks AF_NETLINK / NFNL_SUBSYS_IPSET directly:
many `IPSET_CMD_ADD` messages packed into one sendmsg, acks read back in
one recv, no fork anywhere on the path.

Two layers, split so CI can cover the wire format without root:

* pure encoders (`encode_ipset_add`, `encode_batch`) — bytes in, bytes
  out, golden-tested in tests/unit/test_ipset_netlink.py against
  strace-verified frames;
* `IpsetBatchWriter` — a bounded background queue draining into netlink
  sends, with the hardening contract: enqueue never blocks and never
  raises (overflow sheds the OLDEST entries, counted), any netlink
  failure falls back losslessly to the per-entry subprocess shim
  (idempotent `-exist` adds), and a circuit breaker routes straight to
  subprocess while netlink is broken instead of paying a failed syscall
  per batch.  Every failure is counted in effectors/ipset_stats.py
  (`banjax_ipset_errors_total{path}`).

IPv6 note: the banjax set is created `hash:ip` (family inet), so only
IPv4 entries are encoded; anything else rides the subprocess fallback
untouched — same behavior as before, counted as fallback.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
from collections import deque
from typing import List, Optional, Tuple

from banjax_tpu.effectors.ipset_stats import get_stats
from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.breaker import CircuitBreaker

log = logging.getLogger(__name__)

# ---- netlink / nfnetlink / ipset wire constants (linux uapi) ----
NETLINK_NETFILTER = 12
NLM_F_REQUEST = 0x1
NLM_F_ACK = 0x4
NLMSG_ERROR = 0x2
NLMSG_HDRLEN = 16

NFNL_SUBSYS_IPSET = 6
IPSET_CMD_ADD = 9
IPSET_PROTOCOL = 6

AF_INET = 2
NFNETLINK_V0 = 0

IPSET_ATTR_PROTOCOL = 1
IPSET_ATTR_SETNAME = 2
IPSET_ATTR_DATA = 7
IPSET_ATTR_IP = 1          # inside IPSET_ATTR_DATA
IPSET_ATTR_TIMEOUT = 6     # inside IPSET_ATTR_DATA
IPSET_ATTR_IPADDR_IPV4 = 1  # inside IPSET_ATTR_IP

NLA_F_NESTED = 0x8000
NLA_F_NET_BYTEORDER = 0x4000
NLA_HDRLEN = 4


def _align4(n: int) -> int:
    return (n + 3) & ~3


def _nla(attr_type: int, payload: bytes) -> bytes:
    """One netlink attribute: 4-byte header, payload, pad to 4."""
    length = NLA_HDRLEN + len(payload)
    return struct.pack("=HH", length, attr_type) + payload + b"\x00" * (
        _align4(length) - length
    )


def encode_ipset_add(set_name: str, ip: str, timeout_seconds: int,
                     seq: int) -> bytes:
    """One complete `IPSET_CMD_ADD` netlink message for an IPv4 entry —
    nlmsghdr + nfgenmsg + (PROTOCOL, SETNAME, nested DATA{nested IP
    {IPADDR_IPV4}, TIMEOUT}).  Raises OSError for a non-IPv4 `ip`
    (callers route those to the subprocess shim)."""
    addr = socket.inet_pton(socket.AF_INET, ip)  # OSError on non-IPv4
    payload = _nla(IPSET_ATTR_PROTOCOL, struct.pack("=B", IPSET_PROTOCOL))
    payload += _nla(IPSET_ATTR_SETNAME, set_name.encode() + b"\x00")
    ip_nested = _nla(IPSET_ATTR_IPADDR_IPV4 | NLA_F_NET_BYTEORDER, addr)
    data = _nla(IPSET_ATTR_IP | NLA_F_NESTED, ip_nested)
    data += _nla(IPSET_ATTR_TIMEOUT | NLA_F_NET_BYTEORDER,
                 struct.pack(">I", timeout_seconds))
    payload += _nla(IPSET_ATTR_DATA | NLA_F_NESTED, data)

    nfgen = struct.pack("=BBH", AF_INET, NFNETLINK_V0, 0)
    msg_type = (NFNL_SUBSYS_IPSET << 8) | IPSET_CMD_ADD
    length = NLMSG_HDRLEN + len(nfgen) + len(payload)
    header = struct.pack("=IHHII", length, msg_type,
                         NLM_F_REQUEST | NLM_F_ACK, seq, 0)
    return header + nfgen + payload


def encode_batch(set_name: str, entries: List[Tuple[str, int]],
                 seq_start: int = 1) -> Tuple[bytes, List[str]]:
    """Pack many adds into one sendmsg buffer.  Returns (buffer,
    skipped_ips) — entries netlink cannot carry (non-IPv4) are returned
    for the caller to route through the subprocess shim."""
    out = []
    skipped = []
    seq = seq_start
    for ip, timeout in entries:
        try:
            out.append(encode_ipset_add(set_name, ip, timeout, seq))
        except OSError:
            skipped.append(ip)
            continue
        seq += 1
    return b"".join(out), skipped


def parse_acks(buf: bytes) -> List[int]:
    """Error codes from a kernel ack buffer, one per NLMSG_ERROR message
    (0 = success, negative errno otherwise)."""
    codes = []
    off = 0
    while off + NLMSG_HDRLEN <= len(buf):
        length, msg_type, _flags, _seq, _pid = struct.unpack_from(
            "=IHHII", buf, off
        )
        if length < NLMSG_HDRLEN:
            break
        if msg_type == NLMSG_ERROR and off + NLMSG_HDRLEN + 4 <= len(buf):
            (err,) = struct.unpack_from("=i", buf, off + NLMSG_HDRLEN)
            codes.append(err)
        off += _align4(length)
    return codes


class IpsetBatchWriter:
    """Bounded background queue → coalesced netlink sends, subprocess
    fallback.  `enqueue` is the only producer API and it never blocks
    and never raises — the ban path must not stall on the kernel edge."""

    def __init__(self, ipset, max_queue: int = 1024,
                 flush_interval: float = 0.05,
                 breaker: Optional[CircuitBreaker] = None):
        self._ipset = ipset  # effectors/ipset.py IpsetInstance (fallback + name)
        self._max_queue = max_queue
        self._flush_interval = flush_interval
        self._queue: deque = deque()
        self._lock = threading.Lock()
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._seq = 1
        self._sock: Optional[socket.socket] = None
        self.stats = get_stats()
        self.stats.set_depth_fn(self.queue_depth)
        # consecutive netlink failures open the breaker: batches route
        # straight to subprocess (still lossless) until the recovery
        # window elapses and a half-open probe re-tries netlink
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=3, recovery_seconds=30.0, name="ipset-netlink"
        )
        self._thread = threading.Thread(
            target=self._drain_loop, name="ipset-netlink", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------ producer

    def enqueue(self, ip: str, timeout_seconds: int) -> None:
        """Queue one ban for the kernel set.  Overflow sheds the OLDEST
        queued entry (counted) — the newest ban is the one the attack is
        riding on right now."""
        with self._lock:
            while len(self._queue) >= self._max_queue:
                self._queue.popleft()
                self.stats.note_shed()
            self._queue.append((ip, timeout_seconds))
        self._kick.set()

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    # ------------------------------------------------------------ consumer

    def _take_batch(self) -> List[Tuple[str, int]]:
        with self._lock:
            batch = list(self._queue)
            self._queue.clear()
        return batch

    def _drain_loop(self) -> None:
        while not self._stop.is_set():
            self._kick.wait()
            if self._stop.is_set():
                break
            self._kick.clear()
            # small coalescing window: bursts arriving while we sleep
            # ride the same sendmsg
            self._stop.wait(self._flush_interval)
            batch = self._take_batch()
            if batch:
                self._flush(batch)
        # final drain so close() loses nothing
        batch = self._take_batch()
        if batch:
            self._flush(batch)

    def _flush(self, batch: List[Tuple[str, int]]) -> None:
        if self.breaker.allow():
            try:
                skipped = self._send_netlink(batch)
                self.breaker.record_success()
            except Exception as e:  # noqa: BLE001 — route, never raise
                self.breaker.record_failure()
                self.stats.note_error("netlink")
                log.warning("ipset netlink send failed (%s); "
                            "falling back to subprocess for %d entries",
                            e, len(batch))
                skipped = [ip for ip, _ in batch]
        else:
            skipped = [ip for ip, _ in batch]
        if skipped:
            timeouts = dict(batch)
            self._fallback(
                [(ip, timeouts[ip]) for ip in skipped if ip in timeouts]
            )

    def _send_netlink(self, batch: List[Tuple[str, int]]) -> List[str]:
        """Returns IPs the netlink path did not cover (non-IPv4, or
        per-entry kernel NACKs); raises on transport-level failure."""
        failpoints.check("ipset.netlink.send")
        buf, skipped = encode_batch(self._ipset.name, batch, self._seq)
        if not buf:
            return skipped
        n_msgs = len(batch) - len(skipped)
        self._seq += n_msgs
        sock = self._socket()
        try:
            sock.send(buf)
            acks = self._read_acks(sock, n_msgs)
        except OSError:
            self._close_socket()
            raise
        bad = sum(1 for code in acks if code != 0)
        if bad:
            # per-entry NACKs (e.g. set missing an entry slot): re-route
            # the whole batch — subprocess adds are `-exist`-idempotent,
            # so double-applying the acked ones is harmless
            self.stats.note_error("netlink", bad)
            return skipped + [ip for ip, _ in batch]
        self.stats.note_batch(n_msgs)
        return skipped

    def _read_acks(self, sock: socket.socket, expected: int) -> List[int]:
        acks: List[int] = []
        while len(acks) < expected:
            chunk = sock.recv(65536)
            if not chunk:
                break
            acks.extend(parse_acks(chunk))
        return acks

    def _socket(self) -> socket.socket:
        if self._sock is None:
            sock = socket.socket(socket.AF_NETLINK, socket.SOCK_RAW,
                                 NETLINK_NETFILTER)
            sock.settimeout(2.0)
            sock.bind((0, 0))
            self._sock = sock
        return self._sock

    def _close_socket(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _fallback(self, entries: List[Tuple[str, int]]) -> None:
        self.stats.note_fallback(len(entries))
        for ip, timeout in entries:
            try:
                self._ipset.add(ip, timeout)
            except Exception as e:  # noqa: BLE001 — counted, never raised
                self.stats.note_error("subprocess")
                log.error("ipset fallback add failed for %s: %s", ip, e)

    def close(self) -> None:
        """Stop the drain thread; whatever is still queued is flushed on
        the way out (the loop's final drain)."""
        self._stop.set()
        self._kick.set()
        self._thread.join(timeout=5)
        self._close_socket()
