"""Banner: the ban effector every decision source streams into.

Reference behavior: /root/reference/internal/iptables.go:117-331 — an
interface (mockable in tests) whose implementation (1) inserts an expiring
Decision into the dynamic lists with TTL expiring_decision_ttl_seconds,
(2) escalates IptablesBlock to an ipset add (skipping localhost, standalone
testing, and already-banned IPs), and (3) writes structured JSON ban-log
lines — to banning_log_file, or to the `_temp` variant when the host is in
disable_logging (filebeat routes those to a to-be-deleted ES index).

This is the "Decision-list populator boundary" the TPU matcher streams
candidate decisions through (BASELINE.json).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import List, Optional, TextIO

from banjax_tpu.config.schema import Config
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.model import Decision
from banjax_tpu.effectors.ipset import IpsetInstance

log = logging.getLogger(__name__)

# Field order matches the reference LogJson struct (iptables.go:164-177) so
# the serialized lines are byte-identical.
def _log_json(
    path: str,
    timestring: str,
    trigger: str,
    client_ua: str,
    client_ip: str,
    rule_type: str,
    http_method: str,
    http_schema: str,
    http_host: str,
    action: str,
    number_of_fails: int,
    disable_logging: int,
) -> str:
    return json.dumps(
        {
            "path": path,
            "timestring": timestring,
            "trigger": trigger,
            "client_ua": client_ua,
            "client_ip": client_ip,
            "rule_type": rule_type,
            "client_request_method": http_method,
            "http_request_scheme": http_schema,
            "client_request_host": http_host,
            "action": action,
            "number_of_fails": number_of_fails,
            "disable_logging": disable_logging,
        },
        separators=(",", ":"),
    )


def _format_ban_time(unix_seconds: float) -> str:
    # Go layout "2006-01-02T15:04:05" (iptables.go:187) — local time
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(unix_seconds))


class BannerInterface:
    """iptables.go:117-126. Subclasses: Banner (real), MockBanner (tests)."""

    def ban_or_challenge_ip(self, config: Config, ip: str, decision: Decision, domain: str) -> None:
        raise NotImplementedError

    def log_regex_ban(
        self, config: Config, log_time_unix: float, ip: str, rule_name: str,
        log_line_rest: str, decision: Decision,
    ) -> None:
        raise NotImplementedError

    def log_failed_challenge_ban(
        self, config: Config, ip: str, challenge_type: str, host: str, path: str,
        too_many_failed_challenges_threshold: int, user_agent: str,
        decision: Decision, method: str,
    ) -> None:
        raise NotImplementedError

    def ipset_add(self, config: Config, ip: str) -> None:
        raise NotImplementedError

    def ipset_test(self, config: Config, ip: str) -> bool:
        raise NotImplementedError

    def ipset_list(self) -> List[str]:
        raise NotImplementedError

    def ipset_del(self, ip: str) -> None:
        raise NotImplementedError


class Banner(BannerInterface):
    def __init__(
        self,
        decision_lists: DynamicDecisionLists,
        ban_log_file: TextIO,
        ban_log_file_temp: TextIO,
        ipset_instance: Optional[IpsetInstance],
        netlink_writer=None,
    ):
        self.decision_lists = decision_lists
        self._ban_log = ban_log_file
        self._ban_log_temp = ban_log_file_temp
        self._ipset = ipset_instance
        # batched kernel-edge writer (effectors/ipset_netlink.py): adds
        # ride the coalesced netlink queue; the admin-surface reads
        # (test/list/del) keep the subprocess shim
        self.netlink_writer = netlink_writer
        self._log_lock = threading.Lock()

    @property
    def ipset_batching(self) -> bool:
        return self.netlink_writer is not None and self._ipset is not None

    def ban_or_challenge_ip(self, config: Config, ip: str, decision: Decision, domain: str) -> None:
        """iptables.go:273-294."""
        log.info("BANNER: ban_or_challenge_ip %s %s", ip, decision)
        expires = time.time() + config.expiring_decision_ttl_seconds
        self.decision_lists.update(ip, expires, decision, False, domain)
        if decision == Decision.IPTABLES_BLOCK:
            _ban_ip(config, ip, self)

    def log_regex_ban(
        self, config: Config, log_time_unix: float, ip: str, rule_name: str,
        log_line_rest: str, decision: Decision,
    ) -> None:
        """iptables.go:179-228.

        log_line_rest looks like: `GET localhost:8081 GET /x HTTP/1.1 agent`
        words: [method, host, method, path, proto, ua(+ optional | status)].
        """
        words = log_line_rest.split(" ", 5)
        if len(words) < 6:
            log.warning("log_regex_ban: not enough words")
            return

        disable_logging = 1 if config.disable_logging.get(words[1]) else 0
        # the nginx banjax_format appends "| <status>" after the UA for some
        # rules; keep only what's left of the first vertical bar
        client_ua = words[5].split("|", 1)[0].strip()

        line = _log_json(
            path=words[3],
            timestring=_format_ban_time(log_time_unix),
            trigger=rule_name,
            client_ua=client_ua,
            client_ip=ip,
            rule_type="regex",
            http_method=words[0],
            http_schema="https",  # reference hardcodes https (iptables.go:213)
            http_host=words[1],
            action=str(decision),
            number_of_fails=1,
            disable_logging=disable_logging,
        )
        self._write(line, disable_logging)

    def log_failed_challenge_ban(
        self, config: Config, ip: str, challenge_type: str, host: str, path: str,
        too_many_failed_challenges_threshold: int, user_agent: str,
        decision: Decision, method: str,
    ) -> None:
        """iptables.go:230-271."""
        disable_logging = 1 if config.disable_logging.get(host) else 0
        line = _log_json(
            path=path,
            timestring=_format_ban_time(time.time()),
            trigger=f"failed challenge {challenge_type}",
            client_ua=user_agent,
            client_ip=ip,
            rule_type="failed_challenge",
            http_method=method,
            http_schema="https",
            http_host=host,
            action=str(decision),
            number_of_fails=too_many_failed_challenges_threshold,
            disable_logging=disable_logging,
        )
        self._write(line, disable_logging)

    def _write(self, line: str, disable_logging: int) -> None:
        target = self._ban_log_temp if disable_logging == 1 else self._ban_log
        with self._log_lock:
            target.write(line + "\n")
            target.flush()

    def ipset_add(self, config: Config, ip: str) -> None:
        if self._ipset is None:
            return
        if self.netlink_writer is not None:
            # never blocks, never raises: overflow sheds (counted) and
            # netlink failures fall back to the subprocess shim inside
            # the writer's drain thread
            self.netlink_writer.enqueue(ip, config.iptables_ban_seconds)
            return
        self._ipset.add(ip, config.iptables_ban_seconds)

    def ipset_test(self, config: Config, ip: str) -> bool:
        # iptables.go:300-303: `banned, _ := b.IPSetInstance.Test(ip)` —
        # errors are ignored and read as "not banned"
        if self._ipset is None:
            return False
        try:
            return self._ipset.test(ip)
        except Exception:  # noqa: BLE001 — mirror the ignored error
            return False

    def ipset_list(self) -> List[str]:
        if self._ipset is None:
            return []
        return self._ipset.list_entries()

    def ipset_del(self, ip: str) -> None:
        if self._ipset is not None:
            self._ipset.delete(ip)


def _ban_ip(config: Config, ip: str, banner: BannerInterface) -> None:
    """iptables.go:313-331 — skip localhost, skip in testing, no double ban."""
    log.info("ban_ip: %s timeout %s", ip, config.iptables_ban_seconds)
    if ip == "127.0.0.1":
        log.info("ban_ip: not going to block localhost")
        return
    if config.standalone_testing:
        log.info("ban_ip: not calling ipset in testing")
        return
    if getattr(banner, "ipset_batching", False):
        # the batched writer's adds are idempotent (`-exist` semantics on
        # both the netlink and subprocess paths), so the pre-add Test —
        # one extra fork per ban — buys nothing; skip straight to enqueue
        banner.ipset_add(config, ip)
        return
    if banner.ipset_test(config, ip):
        log.info("ban_ip: no double ban %s", ip)
        return
    try:
        banner.ipset_add(config, ip)
    except Exception as e:  # reference logs and continues (iptables.go:328-330)
        log.error("ban_ip ipset add failed: %s", e)
