"""Seeded chaos schedules: failpoint episodes over a scenario stream.

A ChaosSchedule deterministically places N fault episodes across a
scenario's event stream.  Each episode arms ONE failpoint
(resilience/failpoints.py) just before a chosen event index and settles
it when the next episode starts (or at finish()): the runner records how
often it actually fired and captures one flight-recorder bundle per
episode — the per-episode evidence ROADMAP item 4 asks for.  Organic
captures (breaker trips, shed bursts, SLO breaches) still fire on top;
the explicit per-episode capture guarantees the evidence floor even for
faults the engine absorbs without tripping anything.

The default point set is every failpoint on the pipeline's driven path;
tailer-fed runs add `tailer.open` (rotation reopen faults).  Kafka-fed
runs (ScenarioRunner's `kafka_broker` mode: commands produced into an
in-process broker and drained by a REAL KafkaReader/KafkaWriter pair
over the wire protocol) add `kafka.read`/`kafka.send`, so the
reconnect-with-backoff and held-report-retry loops take faults during
soak, not only in tests/faults/test_kafka_faults.py.
"""

from __future__ import annotations

import dataclasses
import random
from typing import List, Optional, Sequence

from banjax_tpu.obs import flightrec
from banjax_tpu.resilience import failpoints

# failpoints that fire on the ScenarioRunner's driven path
PIPELINE_POINTS = (
    "pipeline.encode",
    "pipeline.submit",
    "pipeline.collect",
    "pipeline.drain",
    "matcher.device",
    "matcher.resolve",
)
TAILER_POINTS = PIPELINE_POINTS + ("tailer.open",)
KAFKA_POINTS = PIPELINE_POINTS + ("kafka.read", "kafka.send")


@dataclasses.dataclass
class Episode:
    point: str
    count: int               # bounded injections per episode
    probability: float
    at_event: int            # armed just before this event index
    fired: int = 0           # observed after settlement
    bundle: Optional[str] = None  # flight-recorder bundle name


class ChaosSchedule:
    def __init__(self, seed: int, n_events: int,
                 points: Sequence[str] = PIPELINE_POINTS,
                 episodes: int = 4):
        rng = random.Random(seed)
        episodes = max(1, min(episodes, max(1, n_events - 1)))
        # distinct, sorted injection sites strictly inside the stream
        sites = sorted(rng.sample(range(1, max(2, n_events)), episodes))
        order = list(points)
        rng.shuffle(order)
        self.episodes: List[Episode] = [
            Episode(
                point=order[i % len(order)],
                count=rng.randint(1, 3),
                probability=1.0 if rng.random() < 0.7 else 0.5,
                at_event=site,
            )
            for i, site in enumerate(sites)
        ]
        self._active: Optional[Episode] = None
        self._next = 0
        self._quiesce = None

    # ---- runner hooks ----

    def bind(self, quiesce) -> None:
        """Install the runner's quiesce callable (pipeline flush): before
        an episode settles, every batch admitted while it was armed is
        driven through the armed stage, so `fired` reflects the episode
        instead of racing the stage threads."""
        self._quiesce = quiesce

    def before_event(self, index: int) -> None:
        """Called by the runner before dispatching event `index`."""
        while (
            self._next < len(self.episodes)
            and self.episodes[self._next].at_event <= index
        ):
            ep = self.episodes[self._next]
            self._settle_active()
            failpoints.arm(
                ep.point, mode="error", count=ep.count,
                probability=ep.probability, seed=ep.at_event,
            )
            self._active = ep
            self._next += 1

    def finish(self) -> None:
        """Settle the last episode; leaves no failpoint armed."""
        self._settle_active()

    def _settle_active(self) -> None:
        ep = self._active
        if ep is None:
            return
        if self._quiesce is not None:
            self._quiesce()
        ep.fired = failpoints.fired_count(ep.point)
        failpoints.disarm(ep.point)
        # the per-episode evidence bundle: captured AFTER the episode so
        # the trace ring / metrics / provenance show its effect.  The
        # runner installs a debounce-free recorder, so this never returns
        # None while one is installed.
        ep.bundle = flightrec.notify(
            f"chaos-{ep.point}",
            f"episode at event {ep.at_event}: count={ep.count} "
            f"p={ep.probability} fired={ep.fired}",
        )
        self._active = None

    # ---- report ----

    def rows(self) -> List[dict]:
        return [dataclasses.asdict(ep) for ep in self.episodes]
