"""Seeded chaos schedules: failpoint episodes over a scenario stream.

A ChaosSchedule deterministically places N fault episodes across a
scenario's event stream.  Each episode arms ONE failpoint
(resilience/failpoints.py) just before a chosen event index and settles
it when the next episode starts (or at finish()): the runner records how
often it actually fired and captures one flight-recorder bundle per
episode — the per-episode evidence ROADMAP item 4 asks for.  Organic
captures (breaker trips, shed bursts, SLO breaches) still fire on top;
the explicit per-episode capture guarantees the evidence floor even for
faults the engine absorbs without tripping anything.

The default point set is every failpoint on the pipeline's driven path;
tailer-fed runs add `tailer.open` (rotation reopen faults).  Kafka-fed
runs (ScenarioRunner's `kafka_broker` mode: commands produced into an
in-process broker and drained by a REAL KafkaReader/KafkaWriter pair
over the wire protocol) add `kafka.read`/`kafka.send`, so the
reconnect-with-backoff and held-report-retry loops take faults during
soak, not only in tests/faults/test_kafka_faults.py.
"""

from __future__ import annotations

import dataclasses
import random
from typing import List, Optional, Sequence

from banjax_tpu.obs import flightrec
from banjax_tpu.resilience import failpoints

# failpoints that fire on the ScenarioRunner's driven path
PIPELINE_POINTS = (
    "pipeline.encode",
    "pipeline.submit",
    "pipeline.collect",
    "pipeline.drain",
    "matcher.device",
    "matcher.resolve",
)
TAILER_POINTS = PIPELINE_POINTS + ("tailer.open",)
KAFKA_POINTS = PIPELINE_POINTS + ("kafka.read", "kafka.send")
# membership-layer failpoints: these fire inside fabric worker
# processes (armed over the wire via T_FAILPOINT), not on the
# single-process runner path
GOSSIP_POINTS = (
    "fabric.gossip.ping",
    "fabric.gossip.ack",
    "fabric.membership.update",
)


@dataclasses.dataclass
class Episode:
    point: str
    count: int               # bounded injections per episode
    probability: float
    at_event: int            # armed just before this event index
    fired: int = 0           # observed after settlement
    bundle: Optional[str] = None  # flight-recorder bundle name


class ChaosSchedule:
    def __init__(self, seed: int, n_events: int,
                 points: Sequence[str] = PIPELINE_POINTS,
                 episodes: int = 4):
        rng = random.Random(seed)
        episodes = max(1, min(episodes, max(1, n_events - 1)))
        # distinct, sorted injection sites strictly inside the stream
        sites = sorted(rng.sample(range(1, max(2, n_events)), episodes))
        order = list(points)
        rng.shuffle(order)
        self.episodes: List[Episode] = [
            Episode(
                point=order[i % len(order)],
                count=rng.randint(1, 3),
                probability=1.0 if rng.random() < 0.7 else 0.5,
                at_event=site,
            )
            for i, site in enumerate(sites)
        ]
        self._active: Optional[Episode] = None
        self._next = 0
        self._quiesce = None

    # ---- runner hooks ----

    def bind(self, quiesce) -> None:
        """Install the runner's quiesce callable (pipeline flush): before
        an episode settles, every batch admitted while it was armed is
        driven through the armed stage, so `fired` reflects the episode
        instead of racing the stage threads."""
        self._quiesce = quiesce

    def before_event(self, index: int) -> None:
        """Called by the runner before dispatching event `index`."""
        while (
            self._next < len(self.episodes)
            and self.episodes[self._next].at_event <= index
        ):
            ep = self.episodes[self._next]
            self._settle_active()
            failpoints.arm(
                ep.point, mode="error", count=ep.count,
                probability=ep.probability, seed=ep.at_event,
            )
            self._active = ep
            self._next += 1

    def finish(self) -> None:
        """Settle the last episode; leaves no failpoint armed."""
        self._settle_active()

    def _settle_active(self) -> None:
        ep = self._active
        if ep is None:
            return
        if self._quiesce is not None:
            self._quiesce()
        ep.fired = failpoints.fired_count(ep.point)
        failpoints.disarm(ep.point)
        # the per-episode evidence bundle: captured AFTER the episode so
        # the trace ring / metrics / provenance show its effect.  The
        # runner installs a debounce-free recorder, so this never returns
        # None while one is installed.
        ep.bundle = flightrec.notify(
            f"chaos-{ep.point}",
            f"episode at event {ep.at_event}: count={ep.count} "
            f"p={ep.probability} fired={ep.fired}",
        )
        self._active = None

    # ---- report ----

    def rows(self) -> List[dict]:
        return [dataclasses.asdict(ep) for ep in self.episodes]


@dataclasses.dataclass
class ChurnOp:
    op: str            # kill | join | slow_node | leave
    at_frac: float     # flood position (kill) / phase ordering hint
    detail: dict
    outcome: Optional[dict] = None  # filled by the executing harness


class MembershipChurnSchedule:
    """Seeded plan for one membership-churn episode over a fabric run.

    The dryrun_fabric churn mode (fabric/harness.py, churn=True) is the
    executor: a SIGKILL with the feed paused (detection must be gossip's
    alone), an automatic join (T_JOIN announce + snapshot sync, no
    restarts), a slow-node suspect/refute cycle (sleep failpoint on
    `fabric.gossip.ack`, armed over the wire), and a planned leave
    (drain + LEFT handback, zero shed / zero replay).  The schedule
    contributes the seeded knobs — where in the flood the kill lands
    and how deaf the slow node plays — so two runs with the same seed
    churn identically, the same determinism contract ChaosSchedule
    gives the single-process soak.
    """

    def __init__(self, seed: int,
                 kill_frac_bounds: tuple = (0.3, 0.6),
                 slow_delay_intervals: tuple = (2.5, 4.0)):
        rng = random.Random(seed)
        self.seed = seed
        self.kill_frac = round(rng.uniform(*kill_frac_bounds), 3)
        # the slow node answers probes after this many gossip intervals
        # (> 1 guarantees every direct probe against it times out)
        self.slow_delay_x = round(rng.uniform(*slow_delay_intervals), 2)
        self.ops: List[ChurnOp] = [
            ChurnOp("kill", self.kill_frac, {"feed_paused": True}),
            ChurnOp("join", 1.0, {"via": "gossip announce"}),
            ChurnOp("slow_node", 1.0,
                    {"point": "fabric.gossip.ack",
                     "delay_intervals": self.slow_delay_x}),
            ChurnOp("leave", 1.0, {"graceful": True}),
        ]

    def record(self, op: str, outcome: dict) -> None:
        for entry in self.ops:
            if entry.op == op:
                entry.outcome = outcome
                return

    def rows(self) -> List[dict]:
        return [dataclasses.asdict(entry) for entry in self.ops]
