"""Named attack shapes: deterministic hostile-traffic generators.

Each shape builds a `Scenario`: an ordered event stream (line chunks,
Kafka command batches, log-rotation markers) over a small shared
ruleset, plus everything the oracle needs to predict the exact ban
multiset.  Generation is pure in (name, seed, scale) — `random.Random`
only, fixed epoch — so the same call is byte-identical across runs and
machines (tests/unit/test_scenarios.py hashes the stream to prove it).

Timing model: all virtual timestamps live inside an 8-second span
anchored at T0, and the runner pins the pipeline clock at T0 + 9 s, so
no line is ever stale against the reference's 10 s cutoff and the
fixed-window math is fully determined by the generated timestamps —
wall-clock speed of the run cannot change the oracle.

The shapes (PAPER.md §0 sources 2–4):

  flash_crowd       sudden synchronized burst from a bounded IP pool —
                    every crowd IP must ban
  slow_drip         many IPs under many distinct UAs, each pacing JUST
                    under the rule threshold; a few greedy drippers
                    cross it — precision bait
  rotating_proxies  the all-distinct-IP worst case (maximal slot churn);
                    a handful of repeat offenders hide in the churn
  command_flood     Baskerville command storm through the pipeline's
                    admission buffer (exercises pipeline_command_take_max
                    chopping) over a live line stream
  challenge_storm   challenge-failure shape: a crowd hammering a
                    challenge-decision rule past its threshold
  log_rotation      flash-crowd burst with rotation markers mid-burst
                    (and a never-terminated trailing line) — the tailer
                    must deliver every line exactly once
  benign            clean traffic only: zero bans, zero SLO burn
"""

from __future__ import annotations

import dataclasses
import json
import random
from typing import Callable, Dict, List, Tuple

# fixed virtual epoch: part of the determinism contract
T0 = 1_700_000_000.0
SPAN_S = 8.0           # all line timestamps in [T0, T0 + SPAN_S]
RUN_NOW = T0 + 9.0     # the runner's pinned clock (max age 9 s < 10 s cutoff)

CHUNK_LINES = 256      # lines per LineChunk event (tailer-chunk shaped)

# the shared scenario ruleset: one volumetric GET rule, one tight probe
# rule, one challenge-decision rule — enough to exercise block,
# iptables and challenge effects without a per-scenario compile bill
RULES_YAML = r"""
regexes_with_rates:
  - rule: http_flood
    regex: 'GET /(index|home|assets)'
    interval: 5
    hits_per_interval: 40
    decision: nginx_block
  - rule: login_probe
    regex: '(GET|POST) /(wp-login|xmlrpc)\.php'
    interval: 5
    hits_per_interval: 8
    decision: iptables_block
  - rule: pay_probe
    regex: 'GET /(checkout|api/v1/pay)'
    interval: 4
    hits_per_interval: 12
    decision: challenge
"""

_BENIGN_PATHS = (
    "/about", "/contact", "/robots.txt", "/img/logo.png",
    "/css/site.css", "/news/2026/07",
)
_HOSTS = ("site.example", "shop.example", "news.example")
_BENIGN_UAS = (
    "Mozilla/5.0 (X11; Linux x86_64)", "Safari/604.1", "curl/8.1",
    "Opera/9.80",
)


@dataclasses.dataclass(frozen=True)
class LineChunk:
    """One tailer-shaped delivery of complete log lines."""

    lines: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class CommandBatch:
    """Kafka command messages for the pipeline admission buffer."""

    raws: Tuple[bytes, ...]


@dataclasses.dataclass(frozen=True)
class Rotation:
    """Log-rotation marker: in tailer-fed mode the runner renames the
    live log file here (new inode, writer moves on).  A no-op when the
    stream is submitted directly."""


@dataclasses.dataclass
class Scenario:
    name: str
    seed: int
    scale: float
    rules_yaml: str
    events: List[object]           # LineChunk | CommandBatch | Rotation
    benign: bool                   # oracle expects ZERO bans
    expected_command_ips: Tuple[str, ...] = ()
    notes: Dict[str, object] = dataclasses.field(default_factory=dict)

    def lines(self) -> List[str]:
        """The flattened line stream in admission order."""
        out: List[str] = []
        for ev in self.events:
            if isinstance(ev, LineChunk):
                out.extend(ev.lines)
        return out

    def n_commands(self) -> int:
        return sum(
            len(ev.raws) for ev in self.events
            if isinstance(ev, CommandBatch)
        )


def _line(ts: float, ip: str, method: str, host: str, path: str,
          ua: str) -> str:
    # the tailer-compatible shape: "<epoch.frac> <ip> <method> <host>
    # <method> <path> HTTP/1.1 <ua> -" — rest starts at the first method
    return f"{ts:.6f} {ip} {method} {host} {method} {path} HTTP/1.1 {ua} -"


def _benign_line(rng: random.Random, t_lo: float, t_hi: float) -> Tuple[float, str]:
    t = T0 + rng.uniform(t_lo, t_hi)
    ip = f"10.9.{rng.randrange(4)}.{rng.randrange(64)}"
    method = rng.choice(("GET", "GET", "GET", "POST", "HEAD"))
    return t, _line(t, ip, method, rng.choice(_HOSTS),
                    rng.choice(_BENIGN_PATHS), rng.choice(_BENIGN_UAS))


def _chunked(timed: List[Tuple[float, str]],
             chunk: int = CHUNK_LINES) -> List[LineChunk]:
    """Sort by virtual time (stable) and split into tailer-sized chunks."""
    timed.sort(key=lambda p: p[0])
    lines = [ln for _, ln in timed]
    return [
        LineChunk(tuple(lines[i: i + chunk]))
        for i in range(0, len(lines), chunk)
    ]


def _scenario(name, seed, scale, events, benign=False, notes=None,
              expected_command_ips=()) -> Scenario:
    return Scenario(
        name=name, seed=seed, scale=scale, rules_yaml=RULES_YAML,
        events=events, benign=benign, notes=notes or {},
        expected_command_ips=tuple(expected_command_ips),
    )


# ---------------------------------------------------------------- shapes


def flash_crowd(seed: int, scale: float = 1.0) -> Scenario:
    """A quiet baseline, then a synchronized 2-second burst: every crowd
    IP exceeds http_flood's 40 hits / 5 s and must ban."""
    rng = random.Random(seed)
    n_crowd = max(4, int(32 * scale))
    hits = 56  # > hits_per_interval within one window
    timed = [_benign_line(rng, 0.0, SPAN_S) for _ in range(n_crowd * 12)]
    for k in range(n_crowd):
        ip = f"10.1.{k >> 8}.{k & 0xFF}"
        ua = rng.choice(_BENIGN_UAS)
        for _ in range(hits):
            t = T0 + rng.uniform(4.0, 6.0)  # the burst window
            timed.append((t, _line(t, ip, "GET", _HOSTS[0],
                                   "/index.html", ua)))
    return _scenario(
        "flash_crowd", seed, scale, _chunked(timed),
        notes={"crowd_ips": n_crowd, "hits_per_ip": hits},
    )


def slow_drip(seed: int, scale: float = 1.0) -> Scenario:
    """Many IPs under many DISTINCT user agents, each pacing login
    probes just under the 8 hits / 5 s threshold; a few greedy drippers
    burst past it.  The oracle expects bans for the greedy set only —
    banning the paced set is a precision failure."""
    rng = random.Random(seed)
    n_drip = max(8, int(96 * scale))
    n_greedy = max(1, n_drip // 24)
    timed = [_benign_line(rng, 0.0, SPAN_S) for _ in range(n_drip * 4)]
    for k in range(n_drip):
        ip = f"10.2.{k >> 8}.{k & 0xFF}"
        ua = f"DripAgent-{k}/{1 + k % 7}.{k % 10}"  # many-UA signature
        # 6 probes spread over the full span: never 9 inside any 5 s
        # fixed window that starts at the first probe
        for j in range(6):
            t = T0 + (j * SPAN_S / 6.0) + rng.uniform(0.0, 0.4)
            timed.append((t, _line(t, ip, "GET", _HOSTS[1],
                                   "/wp-login.php", ua)))
    for k in range(n_greedy):
        ip = f"10.3.0.{k}"
        ua = f"GreedyAgent-{k}/1.0"
        for _ in range(12):  # > 8 inside a 2 s burst
            t = T0 + 2.0 + rng.uniform(0.0, 2.0)
            timed.append((t, _line(t, ip, "POST", _HOSTS[1],
                                   "/xmlrpc.php", ua)))
    return _scenario(
        "slow_drip", seed, scale, _chunked(timed),
        notes={"drip_ips": n_drip, "greedy_ips": n_greedy},
    )


def rotating_proxies(seed: int, scale: float = 1.0) -> Scenario:
    """The all-distinct-IP worst case: every request from a fresh proxy
    exit, maximal window-slot churn, no single IP near a threshold — the
    engine must survive the churn WITHOUT banning the rotation, while
    still catching the few repeat offenders hidden inside it."""
    rng = random.Random(seed)
    n_distinct = max(64, int(2048 * scale))
    n_repeat = 3
    timed = []
    for k in range(n_distinct):
        ip = f"11.{(k >> 16) & 0xFF}.{(k >> 8) & 0xFF}.{k & 0xFF}"
        t = T0 + rng.uniform(0.0, SPAN_S)
        timed.append((t, _line(t, ip, "GET", _HOSTS[0], "/index.html",
                               rng.choice(_BENIGN_UAS))))
    for k in range(n_repeat):
        ip = f"12.0.0.{k + 1}"
        for _ in range(50):  # > 40 within a 2 s slice of the churn
            t = T0 + 3.0 + rng.uniform(0.0, 2.0)
            timed.append((t, _line(t, ip, "GET", _HOSTS[0], "/home",
                                   "curl/8.1")))
    return _scenario(
        "rotating_proxies", seed, scale, _chunked(timed),
        notes={"distinct_ips": n_distinct, "repeat_offenders": n_repeat},
    )


def command_flood(seed: int, scale: float = 1.0) -> Scenario:
    """Baskerville command storm: thousands of block/challenge commands
    ride the pipeline's admission buffer interleaved with a live line
    stream.  Batches are larger than pipeline_command_take_max (1024) so
    the encode stage must chop them instead of letting one giant
    dispatch starve line batching."""
    rng = random.Random(seed)
    n_cmds = max(256, int(3072 * scale))
    timed = [_benign_line(rng, 0.0, SPAN_S) for _ in range(n_cmds // 2)]
    for k in range(8):  # a light concurrent attack so lines still ban
        ip = f"10.4.0.{k}"
        for _ in range(56):
            t = T0 + rng.uniform(2.0, 5.0)
            timed.append((t, _line(t, ip, "GET", _HOSTS[2],
                                   "/assets/app.js", "curl/8.1")))
    chunks = _chunked(timed)
    cmd_ips = []
    raws = []
    for k in range(n_cmds):
        ip = f"198.51.{(k >> 8) & 0xFF}.{k & 0xFF}"
        cmd_ips.append(ip)
        name = "block_ip" if rng.random() < 0.7 else "challenge_ip"
        raws.append(json.dumps(
            {"Name": name, "Value": ip, "host": _HOSTS[0]},
            sort_keys=True,
        ).encode())
    # two oversized batches dropped mid-stream: each > take_max
    half = len(raws) // 2
    mid = max(1, len(chunks) // 3)
    events: List[object] = list(chunks[:mid])
    events.append(CommandBatch(tuple(raws[:half])))
    events.extend(chunks[mid: 2 * mid])
    events.append(CommandBatch(tuple(raws[half:])))
    events.extend(chunks[2 * mid:])
    return _scenario(
        "command_flood", seed, scale, events,
        expected_command_ips=cmd_ips,
        notes={"commands": n_cmds, "command_batches": 2},
    )


def challenge_storm(seed: int, scale: float = 1.0) -> Scenario:
    """Challenge-failure storm: a crowd hammering the challenge-decision
    rule past its threshold — the reference's repeated-challenge-failure
    shape expressed as tailer traffic.  Every storm IP must draw
    (repeated) challenge decisions.  The runner then pushes the same
    clients through the real challenge plane (issue -> solve -> verify
    -> failure state): a seeded `solver_fraction` of them solve the PoW
    cookie and pass, the rest fail until the failed-challenge rate
    limit bans them (runtime.ScenarioRunner._challenge_loop)."""
    rng = random.Random(seed)
    n_storm = max(8, int(48 * scale))
    timed = [_benign_line(rng, 0.0, SPAN_S) for _ in range(n_storm * 8)]
    for k in range(n_storm):
        ip = f"10.5.{k >> 8}.{k & 0xFF}"
        ua = f"ChallengeBot-{k}/2.{k % 5}"
        for _ in range(20):  # > 12 per 4 s window
            t = T0 + 1.0 + rng.uniform(0.0, 3.0)
            timed.append((t, _line(t, ip, "GET", _HOSTS[1], "/checkout",
                                   ua)))
    return _scenario(
        "challenge_storm", seed, scale, _chunked(timed),
        notes={"storm_ips": n_storm, "solver_fraction": 0.25},
    )


def log_rotation(seed: int, scale: float = 1.0) -> Scenario:
    """Flash-crowd burst with the access log rotated mid-burst (three
    times): the tailer must reopen by inode WITHOUT dropping the bytes
    still in the old file or duplicating any line.  Direct-submit runs
    treat the markers as no-ops, so the same oracle judges both modes."""
    base = flash_crowd(seed, scale)
    chunks = [ev for ev in base.events if isinstance(ev, LineChunk)]
    n = len(chunks)
    rot_at = {i for i in (n // 4, n // 2, (3 * n) // 4) if 0 < i < n}
    if not rot_at and n > 1:
        rot_at = {1}
    events: List[object] = []
    for i, ch in enumerate(chunks):
        if i in rot_at:
            events.append(Rotation())
        events.append(ch)
    return _scenario(
        "log_rotation", seed, scale, events,
        notes={**base.notes, "rotations": len(rot_at)},
    )


def benign(seed: int, scale: float = 1.0) -> Scenario:
    """Clean traffic only: the oracle expects zero bans, and the runner
    additionally asserts banjax_slo_breached stays 0 end to end."""
    rng = random.Random(seed)
    n = max(256, int(4096 * scale))
    timed = [_benign_line(rng, 0.0, SPAN_S) for _ in range(n)]
    return _scenario("benign", seed, scale, _chunked(timed), benign=True,
                     notes={"lines": n})


# ------------------------------------------- mega-state streaming shape

# the mega noise paths are RULE-NEUTRAL by construction: a slot-REFUSED
# row that matches a rule still accrues host window state (the
# bounded-ban-delay contract), so 10M matching noise IPs would grow host
# state without bound — neutral paths keep refused noise stateless while
# slot churn (the thing the A/B measures) is match-independent anyway
_MEGA_NOISE_PATHS = ("/about", "/contact", "/robots.txt", "/news/2026/07")
MEGA_OFFENDER_HITS = 50  # > http_flood's 40/5s inside a 2 s slice


def _mega_offender_timed(
    seed: int, n_repeat: int = 3
) -> List[Tuple[float, str]]:
    """The repeat offenders hidden in the mega rotation, sorted by time.
    Shared verbatim by the stream generator and the oracle scenario so
    the offender sub-stream is byte-identical in both."""
    rng = random.Random(seed)
    timed = []
    for k in range(n_repeat):
        ip = f"203.0.113.{k + 1}"  # TEST-NET-3: never collides with noise
        for _ in range(MEGA_OFFENDER_HITS):
            t = T0 + 3.0 + rng.uniform(0.0, 2.0)
            timed.append((t, _line(t, ip, "GET", _HOSTS[0], "/home",
                                   "curl/8.1")))
    timed.sort(key=lambda p: p[0])
    return timed


def mega_offenders(seed: int, n_repeat: int = 3) -> Scenario:
    """Offender-only mini Scenario: the oracle input for mega runs.

    The mega noise is rule-neutral, so the full stream's expected ban
    multiset equals `oracle.expected_bans` over just the offenders —
    per-(ip, rule) fixed windows make the noise interleaving irrelevant.
    Each offender lands exactly one http_flood ban (hit 41 exceeds 40
    and resets to 0; the remaining 9 hits cannot re-fire)."""
    timed = list(_mega_offender_timed(seed, n_repeat))
    return _scenario(
        "mega_rotating_proxies", seed, float(n_repeat), _chunked(timed),
        notes={"repeat_offenders": n_repeat,
               "hits_per_offender": MEGA_OFFENDER_HITS},
    )


def mega_rotating_proxies_stream(seed: int, n_distinct: int,
                                 n_repeat: int = 3, chunk: int = 16384):
    """rotating_proxies at mega scale: a GENERATOR of line chunks, never
    materializing the stream — 10M+ distinct IPs in bounded memory (one
    chunk of strings plus the 150-line offender list).

    Noise: the k-th of `n_distinct` IPs fires exactly one rule-neutral
    request at t = T0 + SPAN_S*k/n_distinct (evenly spaced, so the
    stream is time-sorted by construction and pure in (seed, n_distinct)
    — `seed` jitters only the offender sub-stream).  Offenders: the same
    `_mega_offender_timed` lines the oracle scenario uses, merged in
    timestamp order.  Chunks are `chunk` lines (device-batch shaped, not
    tailer-shaped: this stream exists to drive consume_lines directly)."""
    offenders = _mega_offender_timed(seed, n_repeat)
    oi, on = 0, len(offenders)
    buf: List[str] = []
    for k in range(n_distinct):
        t = T0 + SPAN_S * k / n_distinct
        while oi < on and offenders[oi][0] <= t:
            buf.append(offenders[oi][1])
            oi += 1
            if len(buf) >= chunk:
                yield buf
                buf = []
        ip = (f"{10 + (k >> 24)}.{(k >> 16) & 0xFF}."
              f"{(k >> 8) & 0xFF}.{k & 0xFF}")
        buf.append(_line(t, ip, "GET", _HOSTS[k % len(_HOSTS)],
                         _MEGA_NOISE_PATHS[k & 3],
                         _BENIGN_UAS[(k >> 2) & 3]))
        if len(buf) >= chunk:
            yield buf
            buf = []
    buf.extend(ln for _, ln in offenders[oi:])
    while len(buf) >= chunk:
        yield buf[:chunk]
        buf = buf[chunk:]
    if buf:
        yield buf


SHAPES: Dict[str, Callable[..., Scenario]] = {
    "flash_crowd": flash_crowd,
    "slow_drip": slow_drip,
    "rotating_proxies": rotating_proxies,
    "command_flood": command_flood,
    "challenge_storm": challenge_storm,
    "log_rotation": log_rotation,
    "benign": benign,
}


def generate(name: str, seed: int = 1234, scale: float = 1.0) -> Scenario:
    try:
        shape = SHAPES[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; known: {sorted(SHAPES)}"
        ) from None
    return shape(seed, scale)
