"""Ground-truth ban oracle: an independent reference-semantics simulator.

Given a scenario's line stream in admission order and the scenario's
compiled ruleset, predict the EXACT multiset of (ip, rule) ban events
the reference engine must emit.  This is deliberately a second,
self-contained implementation of the fixed-window semantics
(rate_limit.go quirks included) rather than a call into
banjax_tpu/decisions/rate_limit.py — the oracle judging the engine must
not share the engine's code.

Quirks reproduced exactly (the contract the differential suites pin):

  * timestamps parse as int(float(text) * 1e9) — Go's float64-multiply
    truncation;
  * the window restarts (hits := 1) when ts - start > interval_ns,
    STRICTLY greater;
  * exceeded when hits > hits_per_interval, STRICTLY greater, and the
    hit count then resets to 0 (not 1 — rate_limit.go:71);
  * per-site rules first, then global rules, regex unanchored-searched
    over `rest` (everything after "<ts> <ip> ").

Scenario shapes keep every timestamp within the 10 s staleness cutoff
of the runner's pinned clock, so staleness never enters the oracle; a
guard assert catches a shape that violates that contract.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Tuple

from banjax_tpu.scenarios.shapes import RUN_NOW, Scenario

OLD_LINE_CUTOFF_SECONDS = 10.0  # regex_rate_limiter.go:164


def expected_bans(scenario: Scenario, config) -> List[Tuple[str, str]]:
    """(ip, rule_name) ban events, in stream order, for the scenario's
    line stream under `config`'s compiled rules."""
    # (ip, rule) -> [num_hits, interval_start_ns]
    windows: Dict[Tuple[str, str], List[int]] = {}
    bans: List[Tuple[str, str]] = []
    for line in scenario.lines():
        parts = line.split(" ", 2)
        if len(parts) < 3:
            continue
        ts_ns = int(float(parts[0]) * 1e9)
        ip, rest = parts[1], parts[2]
        sub = rest.split(" ", 2)
        if len(sub) < 3:
            continue
        host = sub[1]
        assert RUN_NOW - ts_ns / 1e9 <= OLD_LINE_CUTOFF_SECONDS, (
            f"scenario {scenario.name} emitted a stale line — shapes must "
            "stay inside the 10 s cutoff so the oracle is exact"
        )
        rules = list(config.per_site_regexes_with_rates.get(host, []))
        rules.extend(config.regexes_with_rates)
        for rule in rules:
            if rule.regex.search(rest) is None:
                continue
            if rule.hosts_to_skip.get(host):
                continue
            state = windows.get((ip, rule.rule))
            if state is None:
                state = [1, ts_ns]
                windows[(ip, rule.rule)] = state
            elif ts_ns - state[1] > rule.interval_ns:
                state[0] = 1
                state[1] = ts_ns
            else:
                state[0] += 1
            if state[0] > rule.hits_per_interval:
                state[0] = 0  # the reference's reset-to-0 quirk
                bans.append((ip, rule.rule))
    return bans


def precision_recall(
    engine_bans: List[Tuple[str, str]],
    oracle_bans: List[Tuple[str, str]],
) -> Tuple[float, float, int]:
    """Multiset precision/recall of the engine's (ip, rule) ban events
    against the oracle's, plus the true-positive count.  Both default to
    1.0 on an empty side so a benign scenario scores clean."""
    eng, orc = Counter(engine_bans), Counter(oracle_bans)
    tp = sum((eng & orc).values())
    precision = tp / sum(eng.values()) if eng else 1.0
    recall = tp / sum(orc.values()) if orc else 1.0
    return precision, recall, tp
