"""ScenarioRunner: feed a generated scenario through the real engine.

One runner = one fresh engine stack (TpuMatcher with device windows,
PipelineScheduler, SLO engine, optional flight recorder) fed one
scenario's event stream, either by direct submit() (the default: fastest,
exercises the full pipeline) or through a real temp file + LogTailer
(`via_tailer=True` — the mode where Rotation markers rotate an actual
inode and the tailer's no-drop/no-dup contract is on trial).

What a run produces (ScenarioReport):

  * throughput + pressure: lines/s over the feed, shed/stale/drain-error
    counts (deltas over the run, warmup excluded);
  * correctness vs ground truth: multiset ban precision/recall against
    the oracle (scenarios/oracle.py) — 1.0/1.0 expected on clean runs,
    honestly degraded under chaos;
  * SLO evidence: per-SLO peak burn rate over the run (sampled on a
    virtual clock) and the final breached set;
  * structural invariants, each a named boolean:
      - accounting:      admitted == processed + shed + drain_errors
      - no_leaked_turns: the fused two-phase pipeline is idle (every
                         order turn settled)
      - no_leaked_pins:  zero outstanding device-window slot pins
      - commands_drained (when the shape carries commands, clean runs)
      - benign_no_bans / benign_slo_clean (benign shapes, clean runs)
  * chaos evidence: per-episode fired counts and one flight-recorder
    bundle per episode (when a recorder directory is given).

The matcher is warmed with rule-neutral traffic before the measured
feed so device-compile time lands outside the SLO/throughput window —
the same discipline every bench mode uses.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Dict, List, Optional, Tuple

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.rate_limit import (
    FailedChallengeRateLimitStates,
    RegexRateLimitStates,
)
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.obs import flightrec as flightrec_mod
from banjax_tpu.scenarios import oracle as oracle_mod
from banjax_tpu.scenarios import stats as scen_stats
from banjax_tpu.scenarios.shapes import (
    _HOSTS,
    RUN_NOW,
    T0,
    CommandBatch,
    LineChunk,
    Rotation,
    Scenario,
)

_WARM_IP = "9.254.254.254"  # outside every shape's IP space


class RecordingBanner:
    """Effect sink for scenario runs: records (ip, rule) ban events and
    decisions instead of touching ipset/dynamic lists — the same role as
    tests' MockBanner, local so the harness has no test-tree import."""

    def __init__(self) -> None:
        self.regex_ban_logs: List[Tuple[str, str]] = []
        self.failed_challenge_ban_logs: List[Tuple[str, str]] = []  # (ip, type)
        self.decisions: List[Tuple[str, str]] = []   # (ip, decision)
        self.ipset: set = set()

    def ban_or_challenge_ip(self, config, ip, decision, domain) -> None:
        self.decisions.append((ip, str(decision)))

    def log_regex_ban(self, config, log_time_unix, ip, rule_name,
                      log_line_rest, decision) -> None:
        self.regex_ban_logs.append((ip, rule_name))

    def log_failed_challenge_ban(self, config, ip, challenge_type, host,
                                 path, threshold, user_agent, decision,
                                 method) -> None:
        self.failed_challenge_ban_logs.append((ip, challenge_type))

    def ipset_add(self, config, ip) -> None:
        self.ipset.add(ip)

    def ipset_test(self, config, ip) -> bool:
        return ip in self.ipset

    def ipset_list(self) -> list:
        return sorted(self.ipset)

    def ipset_del(self, ip) -> None:
        self.ipset.discard(ip)


@dataclasses.dataclass
class EngineParts:
    """One assembled single-process engine stack — the unit the fabric
    replicates per shard.  Built by `build_engine` and shared between
    ScenarioRunner and fabric/worker so both drive the SAME assembly
    (matcher flags, scheduler knobs, pinned virtual clock)."""

    cfg: object
    banner: object
    dynamic_lists: DynamicDecisionLists
    regex_states: RegexRateLimitStates
    matcher: object
    sched: object


def build_engine(
    rules_yaml: str,
    *,
    banner=None,
    single_kernel: str = "auto",
    breaker_recovery_s: float = 0.5,
    latency_budget_ms: float = 180.0,
    buffer_lines: int = 131072,
    max_block_ms: float = 50.0,
    kafka_broker_port: Optional[int] = None,
    kafka_command_topic: str = "scenario.commands",
    kafka_report_topic: str = "scenario.reports",
    cfg_overrides: Optional[Dict[str, object]] = None,
    now_fn=None,
) -> EngineParts:
    """Assemble the full engine (TPU matcher with device windows +
    pipeline scheduler) on the scenario virtual clock.  The banner is
    injectable so the fabric can wrap RecordingBanner with its
    replicating banner without re-stating the assembly."""
    from banjax_tpu.matcher.runner import TpuMatcher
    from banjax_tpu.pipeline import PipelineScheduler

    cfg = config_from_yaml_text(rules_yaml)
    cfg.matcher = "tpu"
    cfg.matcher_device_windows = True
    cfg.pallas_single_kernel = single_kernel
    cfg.breaker_recovery_seconds = breaker_recovery_s
    cfg.expiring_decision_ttl_seconds = 300
    if kafka_broker_port is not None:
        cfg.kafka_brokers = [f"127.0.0.1:{kafka_broker_port}"]
        cfg.kafka_command_topic = kafka_command_topic
        cfg.kafka_report_topic = kafka_report_topic
        cfg.kafka_max_wait_ms = 100
    if cfg_overrides:
        # harness-level knobs (slot admission, warm tier, fabric ids,
        # ...) the scenario's rules_yaml doesn't carry
        for k, v in cfg_overrides.items():
            setattr(cfg, k, v)
    dynamic_lists = DynamicDecisionLists(start_sweeper=False)
    banner = banner if banner is not None else RecordingBanner()
    regex_states = RegexRateLimitStates()
    matcher = TpuMatcher(
        cfg, banner, StaticDecisionLists(cfg), regex_states
    )
    sched = PipelineScheduler(
        lambda: matcher,
        latency_budget_ms=latency_budget_ms,
        buffer_lines=buffer_lines,
        max_block_ms=max_block_ms,
        now_fn=now_fn if now_fn is not None else (lambda: RUN_NOW),
    )
    return EngineParts(
        cfg=cfg, banner=banner, dynamic_lists=dynamic_lists,
        regex_states=regex_states, matcher=matcher, sched=sched,
    )


@dataclasses.dataclass
class ScenarioReport:
    name: str
    seed: int
    scale: float
    mode: str                      # "direct" | "tailer" | "kafka"
    single_kernel: str
    n_lines: int
    n_commands: int
    feed_s: float
    lines_per_sec: float
    shed_lines: int
    drain_error_lines: int
    stale_lines: int
    shed_ratio: float
    fallback_batches: int
    engine_bans: int
    oracle_bans: int
    true_positives: int
    precision: float
    recall: float
    device_p99_ms: Optional[float]
    slo_burn_peak: Dict[str, float]
    slo_breached: Dict[str, bool]
    invariants: Dict[str, bool]
    episodes: List[dict]
    incidents: int
    command_items: int
    # challenge-plane loop results (challenge_storm only, else None):
    # scripted issuance -> solve -> verify -> failure run with exact
    # precision/recall vs the scripted solver/attacker split
    challenge: Optional[dict] = None

    def ok(self) -> bool:
        return all(self.invariants.values())

    def row(self) -> dict:
        return dataclasses.asdict(self)


class ScenarioRunner:
    def __init__(
        self,
        scenario: Scenario,
        *,
        single_kernel: str = "auto",
        chaos=None,
        via_tailer: bool = False,
        tmp_dir: Optional[str] = None,
        flightrec_dir: Optional[str] = None,
        latency_budget_ms: float = 180.0,
        buffer_lines: int = 131072,
        max_block_ms: float = 50.0,
        slo_budget_s: float = 2.0,
        slo_sample_every: int = 4,
        breaker_recovery_s: float = 0.5,
        cfg_overrides: Optional[Dict[str, object]] = None,
        kafka_broker=None,
    ):
        self.scenario = scenario
        self.single_kernel = single_kernel
        self.chaos = chaos
        self.via_tailer = via_tailer
        self.tmp_dir = tmp_dir
        self.flightrec_dir = flightrec_dir
        self.latency_budget_ms = latency_budget_ms
        self.buffer_lines = buffer_lines
        self.max_block_ms = max_block_ms
        self.slo_budget_s = slo_budget_s
        self.slo_sample_every = max(1, slo_sample_every)
        self.breaker_recovery_s = breaker_recovery_s
        self.cfg_overrides = cfg_overrides
        # kafka-fed command mode: an in-process broker (duck-typed:
        # .port / .append / .log_end_offset — tests/fake_kafka_broker)
        # receives every CommandBatch and a REAL KafkaReader drains it
        # over the wire protocol into the pipeline's admission buffer,
        # with a KafkaWriter pushing one report per batch the other way
        # — the mode where kafka.read/kafka.send failpoints fire during
        # soak instead of only in the fault unit tests.
        self.kafka_broker = kafka_broker
        self._commands_handled = 0
        self._kafka_reports_sent = 0

    # ---- engine assembly ----

    def _build(self):
        from banjax_tpu.obs.slo import SloEngine

        parts = build_engine(
            self.scenario.rules_yaml,
            single_kernel=self.single_kernel,
            breaker_recovery_s=self.breaker_recovery_s,
            latency_budget_ms=self.latency_budget_ms,
            buffer_lines=self.buffer_lines,
            max_block_ms=self.max_block_ms,
            kafka_broker_port=(
                self.kafka_broker.port
                if self.kafka_broker is not None else None
            ),
            cfg_overrides=self.cfg_overrides,
        )
        self.cfg = parts.cfg
        self.dynamic_lists = parts.dynamic_lists
        self.banner = parts.banner
        self.regex_states = parts.regex_states
        self.matcher = parts.matcher
        self.sched = parts.sched
        self._vnow = 0.0
        self.slo = SloEngine(
            matcher_getter=lambda: self.matcher,
            pipeline_getter=lambda: self.sched,
            batch_budget_s_fn=lambda: self.slo_budget_s,
            on_breach=lambda name, burn: flightrec_mod.notify(
                f"slo-{name}", f"burn rates {burn}"
            ),
            clock=lambda: self._vnow,
        )
        self.flightrec = None
        self._prev_recorder = flightrec_mod.installed()
        if self.flightrec_dir:
            from banjax_tpu.obs.flightrec import FlightRecorder

            self.flightrec = FlightRecorder(
                self.flightrec_dir,
                min_interval_s=0.0,   # one bundle per episode, no debounce
                keep=256,
                metrics_text_fn=self._metrics_text,
                slo_getter=lambda: self.slo,
            )
            flightrec_mod.install(self.flightrec)

    def _metrics_text(self) -> str:
        from banjax_tpu.obs.exposition import render_prometheus

        return render_prometheus(
            self.dynamic_lists, self.regex_states,
            FailedChallengeRateLimitStates(), matcher=self.matcher,
            pipeline=self.sched, slo=self.slo, flightrec=self.flightrec,
        )

    # ---- SLO sampling (virtual clock) ----

    def _slo_tick(self, peaks: Dict[str, float]) -> None:
        self._vnow += 30.0
        self.slo.sample()
        for slo_name, windows in self.slo.burn_rates().items():
            peak = max(windows.values()) if windows else 0.0
            peaks[slo_name] = max(peaks.get(slo_name, 0.0), peak)

    # ---- command dispatch (the kafka drain-stage handler) ----

    def _handle_command(self, raw: bytes) -> None:
        from banjax_tpu.ingest.kafka_io import handle_command

        try:
            cmd = json.loads(raw)
        except ValueError:
            return
        handle_command(self.cfg, cmd, self.dynamic_lists)
        self._commands_handled += 1

    # ---- kafka-fed command mode ----

    def _kafka_dispatch(self, raw: bytes) -> None:
        """Reader drain-stage handler: readiness pings settle the tail-
        attach race (the reader consumes from latest; its attach moment
        is unobservable), everything else is a scenario command."""
        if b'"scenario_ping"' in raw:
            self._kafka_ready.set()
            return
        self._handle_command(raw)

    def _kafka_start(self) -> dict:
        import queue as queue_mod
        import threading

        from banjax_tpu.ingest import reports
        from banjax_tpu.ingest.kafka_io import KafkaReader, KafkaWriter
        from banjax_tpu.ingest.kafka_wire import WireKafkaTransport
        from banjax_tpu.resilience.backoff import Backoff

        class _Holder:
            def __init__(self, cfg):
                self._cfg = cfg

            def get(self):
                return self._cfg

        # other tests share the module-level report queue: drain it so
        # the produced-report settle counts only this run's reports
        q = reports.get_message_queue()
        while True:
            try:
                q.get_nowait()
            except queue_mod.Empty:
                break

        self._kafka_ready = threading.Event()
        holder = _Holder(self.cfg)
        fast = dict(base=0.05, cap=0.2, jitter=0.0)
        reader = KafkaReader(
            holder, self.dynamic_lists, transport=WireKafkaTransport(),
            backoff=Backoff(**fast), pipeline=self.sched,
        )
        reader.dispatch_raw = self._kafka_dispatch
        writer = KafkaWriter(
            holder, transport=WireKafkaTransport(), backoff=Backoff(**fast)
        )
        reader.start()
        writer.start()
        # the reader attaches at the log tail at an unobservable moment:
        # keep producing pings until one round-trips through the real
        # fetch path + pipeline drain (no fixed sleeps)
        deadline = time.monotonic() + 30
        while not self._kafka_ready.wait(0.05):
            if time.monotonic() > deadline:
                raise RuntimeError("kafka scenario reader never attached")
            self.kafka_broker.append(
                self.cfg.kafka_command_topic, 0, b'{"Name": "scenario_ping"}'
            )
        return {"reader": reader, "writer": writer, "queue": q}

    def _kafka_feed(self, ev: CommandBatch, ctx: dict) -> None:
        """One CommandBatch: produce every raw into the broker's command
        topic (the reader's fetch loop delivers them into the pipeline)
        and push one report the other way through the writer, so BOTH
        kafka failpoints sit on exercised code during the soak."""
        for raw in ev.raws:
            self.kafka_broker.append(self.cfg.kafka_command_topic, 0, raw)
        ctx["queue"].put_nowait(
            json.dumps({"name": "scenario_report",
                        "batch": self._kafka_reports_sent}).encode()
        )
        self._kafka_reports_sent += 1

    def _kafka_settle(self, n_cmds: int) -> None:
        """Wait for the async kafka legs to finish: every command drained
        (clean runs — a kafka.read episode loses the tail-attach window
        by design, exactly the reference's consume-from-latest contract)
        and every report produced (the writer never drops a dequeued
        report, so this converges even across kafka.send faults)."""
        deadline = time.monotonic() + 60
        topic = self.cfg.kafka_report_topic
        while time.monotonic() < deadline:
            self.sched.flush(60)
            cmds_ok = self._commands_handled >= n_cmds
            reports_ok = (
                self.kafka_broker.log_end_offset(topic, 0)
                >= self._kafka_reports_sent
            )
            if cmds_ok and reports_ok:
                return
            time.sleep(0.05)
        if self.chaos is None:
            raise RuntimeError(
                f"kafka scenario did not settle: "
                f"{self._commands_handled}/{n_cmds} commands, "
                f"{self.kafka_broker.log_end_offset(topic, 0)}"
                f"/{self._kafka_reports_sent} reports"
            )

    # ---- the run ----

    def run(self) -> ScenarioReport:
        self._build()
        try:
            return self._run_inner()
        finally:
            flightrec_mod.install(self._prev_recorder)
            self.matcher.close()

    def _warmup(self) -> None:
        """Push compile + sizer settle outside the measured window with
        rule-neutral traffic (single sub-threshold hits from an IP no
        shape uses, so window state and the oracle are untouched)."""
        warm = [
            f"{T0:.6f} {_WARM_IP} GET warm.example GET /about "
            "HTTP/1.1 warm -"
            for _ in range(48)
        ]
        warm.append(
            f"{T0:.6f} {_WARM_IP} GET warm.example GET /index.html "
            "HTTP/1.1 warm -"
        )
        warm.append(
            f"{T0:.6f} {_WARM_IP} GET warm.example GET /checkout "
            "HTTP/1.1 warm -"
        )
        for _ in range(2):
            self.sched.submit(warm)
            if not self.sched.flush(600):
                raise RuntimeError("scenario warmup did not drain")

    def _run_inner(self) -> ScenarioReport:
        sc = self.scenario
        self.sched.start()
        tailer_ctx = self._tailer_start() if self.via_tailer else None
        kafka_ctx = (
            self._kafka_start() if self.kafka_broker is not None else None
        )
        try:
            self._warmup()

            base = self.sched.stats.peek()
            bans_before = len(self.banner.regex_ban_logs)
            peaks: Dict[str, float] = {}
            self.slo.sample()  # baseline AFTER warmup: deltas exclude it

            if self.chaos is not None:
                self.chaos.bind(lambda: self.sched.flush(600))
            t_feed = time.perf_counter()
            for i, ev in enumerate(sc.events):
                if self.chaos is not None:
                    self.chaos.before_event(i)
                if isinstance(ev, LineChunk):
                    if tailer_ctx is not None:
                        self._tailer_write(tailer_ctx, ev, i)
                    else:
                        self.sched.submit(list(ev.lines))
                elif isinstance(ev, CommandBatch):
                    if kafka_ctx is not None:
                        self._kafka_feed(ev, kafka_ctx)
                    else:
                        self.sched.submit_commands(
                            list(ev.raws), self._handle_command
                        )
                elif isinstance(ev, Rotation):
                    if tailer_ctx is not None:
                        self._tailer_rotate(tailer_ctx)
                if (i + 1) % self.slo_sample_every == 0:
                    self._slo_tick(peaks)
            if tailer_ctx is not None:
                self._tailer_settle(
                    tailer_ctx,
                    int(base["PipelineAdmittedLines"])
                    + len(sc.lines()) + sc.n_commands(),
                )
            if kafka_ctx is not None:
                self._kafka_settle(sc.n_commands())
            if not self.sched.flush(600):
                raise RuntimeError(f"scenario {sc.name} did not drain")
            feed_s = max(1e-9, time.perf_counter() - t_feed)
            self._slo_tick(peaks)
            if self.chaos is not None:
                self.chaos.finish()
        finally:
            if kafka_ctx is not None:
                kafka_ctx["reader"].stop()
                kafka_ctx["writer"].stop()
            if tailer_ctx is not None:
                tailer_ctx["tailer"].stop()
                tailer_ctx["writer"].close()
            self.sched.stop()

        challenge = self._challenge_loop()
        return self._report(base, bans_before, peaks, feed_s, challenge)

    # ---- challenge-plane loop (challenge_storm shape) ----

    def _challenge_loop(self) -> Optional[dict]:
        """Drive every storm client through the REAL challenge plane —
        decision_chain's send_or_validate_sha_challenge with the
        scenario banner as effect sink — not a simulation.  A seeded
        fraction of clients solve the PoW cookie they were issued and
        must pass; the rest present garbage cookies until the
        failed-challenge rate limit bans them.  The scripted oracle is
        exact (non-solvers ban, solvers never do), so precision/recall
        below 1.0/1.0 is an engine bug.  All of one client's failures
        land inside a single rate-limit interval — the regime where the
        bounded failure state's drops can only DELAY a ban
        (challenge/failures.py), never un-ban or misban."""
        sc = self.scenario
        n_storm = int(sc.notes.get("storm_ips") or 0)
        if not n_storm:
            return None
        import random as random_mod

        from banjax_tpu.challenge import verifier as challenge_verifier_mod
        from banjax_tpu.challenge.failures import make_failed_challenge_states
        from banjax_tpu.crypto.challenge import solve_challenge_for_testing
        from banjax_tpu.decisions.model import FailAction
        from banjax_tpu.decisions.protected_paths import PasswordProtectedPaths
        from banjax_tpu.httpapi.decision_chain import (
            ChainState,
            RequestInfo,
            ShaChallengeResult,
            send_or_validate_sha_challenge,
        )
        from banjax_tpu.httpapi.rewrite import CHALLENGE_COOKIE_NAME

        cfg = self.cfg
        # the shared scenario ruleset carries no challenge-plane keys:
        # fill in deterministic storm defaults (cfg_overrides still wins
        # — build_engine applied them before we got here)
        if not cfg.hmac_secret:
            cfg.hmac_secret = f"scenario-secret-{sc.seed}"
        if cfg.sha_inv_expected_zero_bits <= 0:
            cfg.sha_inv_expected_zero_bits = 8  # ~256 hashes per solve
        if cfg.sha_inv_cookie_ttl_seconds <= 0:
            cfg.sha_inv_cookie_ttl_seconds = 60
        if cfg.too_many_failed_challenges_threshold <= 0:
            cfg.too_many_failed_challenges_threshold = 3
        if cfg.too_many_failed_challenges_interval_seconds <= 0:
            cfg.too_many_failed_challenges_interval_seconds = 30

        fc_states = make_failed_challenge_states(cfg)
        device = challenge_verifier_mod.from_config(cfg)
        state = ChainState(
            config=cfg,
            static_lists=StaticDecisionLists(cfg),
            dynamic_lists=self.dynamic_lists,
            protected_paths=PasswordProtectedPaths(cfg),
            failed_challenge_states=fc_states,
            banner=self.banner,
            challenge_verifier=device,
        )
        rng = random_mod.Random(sc.seed ^ 0x57012)
        solver_fraction = float(sc.notes.get("solver_fraction", 0.25))
        threshold = cfg.too_many_failed_challenges_threshold
        bans_before = len(self.banner.failed_challenge_ban_logs)
        solvers: set = set()
        attackers: set = set()
        solver_passes = 0
        for k in range(n_storm):
            ip = f"10.5.{(k >> 8) & 0xFF}.{k & 0xFF}"
            req = RequestInfo(
                client_ip=ip,
                requested_host=_HOSTS[1],
                requested_path="/checkout",
                client_user_agent=f"ChallengeBot-{k}/2.{k % 5}",
            )
            if rng.random() < solver_fraction:
                solvers.add(ip)
                # first visit has no cookie: the real 429 issuance path
                resp, _, _ = send_or_validate_sha_challenge(
                    state, req, FailAction.BLOCK
                )
                issued = next(
                    c.value for c in resp.cookies
                    if c.name == CHALLENGE_COOKIE_NAME
                )
                solved = solve_challenge_for_testing(
                    issued, cfg.sha_inv_expected_zero_bits
                )
                req2 = dataclasses.replace(
                    req, cookies={CHALLENGE_COOKIE_NAME: solved}
                )
                _, result, _ = send_or_validate_sha_challenge(
                    state, req2, FailAction.BLOCK
                )
                if result == ShaChallengeResult.PASSED:
                    solver_passes += 1
            else:
                attackers.add(ip)
                # garbage cookies until the rate limit trips the ban
                for _ in range(threshold + 1):
                    reqk = dataclasses.replace(
                        req, cookies={CHALLENGE_COOKIE_NAME: "!bogus!"}
                    )
                    _, _, rate = send_or_validate_sha_challenge(
                        state, reqk, FailAction.BLOCK
                    )
                    if rate.exceeded:
                        break
        banned = {
            ip for ip, _ in
            self.banner.failed_challenge_ban_logs[bans_before:]
        }
        tp = len(banned & attackers)
        precision = tp / len(banned) if banned else 1.0
        recall = tp / len(attackers) if attackers else 1.0
        limit = int(getattr(cfg, "challenge_failure_state_max", 0) or 0)
        return {
            "storm_clients": n_storm,
            "solvers": len(solvers),
            "solver_passes": solver_passes,
            "attackers": len(attackers),
            "banned": len(banned),
            "ban_precision": round(precision, 6),
            "ban_recall": round(recall, 6),
            "verify_path": "device" if device is not None else "cpu",
            "failure_state_entries": len(fc_states),
            "failure_state_max": limit,
            "failure_state_bounded": (
                limit == 0 or len(fc_states) <= limit
            ),
        }

    # ---- tailer-fed mode ----

    def _tailer_start(self) -> dict:
        from banjax_tpu.ingest.tailer import LogTailer

        assert self.tmp_dir, "via_tailer needs tmp_dir"
        path = os.path.join(self.tmp_dir, "scenario-access.log")
        writer = open(path, "a", encoding="utf-8")
        tailer = LogTailer(path, self.sched.submit)
        tailer.start()
        if not tailer.opened.wait(10):
            raise RuntimeError("scenario tailer did not open the log")
        return {"path": path, "writer": writer, "tailer": tailer, "rot": 0}

    def _tailer_write(self, ctx: dict, ev: LineChunk, index: int) -> None:
        # write the chunk; when a Rotation marker is next, leave the
        # final line WITHOUT its newline — the rotation drain must still
        # deliver it (the partial-line half of the no-drop contract)
        nxt = (
            self.scenario.events[index + 1]
            if index + 1 < len(self.scenario.events) else None
        )
        text = "\n".join(ev.lines)
        if not isinstance(nxt, Rotation):
            text += "\n"
        ctx["writer"].write(text)
        ctx["writer"].flush()

    def _tailer_rotate(self, ctx: dict) -> None:
        # the tailer must have OPENED the current generation before it
        # disappears: rotating twice inside one poll interval would
        # orphan a whole file no follower can see (real log movers never
        # do that — the no-drop contract covers the file the tailer
        # holds, whose unread tail the rotation drain recovers)
        tailer = ctx["tailer"]
        deadline = time.monotonic() + 30
        while not tailer.opened.is_set():
            if time.monotonic() > deadline:
                raise RuntimeError("tailer never opened the rotated log")
            time.sleep(0.01)
        tailer.opened.clear()  # re-set by the tailer's reopen
        ctx["writer"].close()
        ctx["rot"] += 1
        os.replace(ctx["path"], f"{ctx['path']}.{ctx['rot']}")
        ctx["writer"] = open(ctx["path"], "a", encoding="utf-8")

    def _tailer_settle(self, ctx: dict, expect_admitted: int) -> None:
        """Wait until the tailer has delivered every generated line
        (warmup lines were submitted directly, so the expected admission
        count is warmup + stream)."""
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            peek = self.sched.stats.peek()
            if peek["PipelineAdmittedLines"] >= expect_admitted:
                return
            time.sleep(0.05)
        raise RuntimeError(
            f"tailer delivered {self.sched.stats.peek()} "
            f"< {expect_admitted} lines"
        )

    # ---- reporting ----

    def _report(self, base: dict, bans_before: int,
                peaks: Dict[str, float], feed_s: float,
                challenge: Optional[dict] = None) -> ScenarioReport:
        sc = self.scenario
        peek = self.sched.stats.peek()

        def delta(key: str) -> int:
            return int(peek[key]) - int(base[key])

        n_lines = len(sc.lines())
        n_cmds = sc.n_commands()
        shed = delta("PipelineShedLines")
        drain_err = delta("PipelineDrainErrorLines")
        stale = delta("PipelineStaleDroppedLines")
        admitted = delta("PipelineAdmittedLines")
        processed = delta("PipelineProcessedLines")

        engine_bans = self.banner.regex_ban_logs[bans_before:]
        oracle_bans = oracle_mod.expected_bans(sc, self.cfg)
        precision, recall, tp = oracle_mod.precision_recall(
            engine_bans, oracle_bans
        )

        chaotic = self.chaos is not None
        fw = getattr(self.matcher, "_fw_pipeline", None)
        dw = getattr(self.matcher, "device_windows", None)
        invariants: Dict[str, bool] = {
            "accounting": admitted == processed + shed + drain_err,
            "no_leaked_turns": fw is None or fw.idle(),
            "no_leaked_pins": (
                dw is None or int(dw._pin_counts.sum()) == 0
            ),
        }
        if n_cmds and not chaotic:
            invariants["commands_drained"] = (
                self._commands_handled == n_cmds
            )
        if sc.benign and not chaotic:
            invariants["benign_no_bans"] = not engine_bans
            invariants["benign_slo_clean"] = not any(
                self.slo.breached().values()
            )
        if chaotic and self.flightrec is not None:
            invariants["bundle_per_episode"] = all(
                ep.bundle for ep in self.chaos.episodes
            )
        if challenge is not None and not chaotic:
            invariants["challenge_ban_exact"] = (
                challenge["ban_precision"] == 1.0
                and challenge["ban_recall"] == 1.0
            )
            invariants["challenge_state_bounded"] = (
                challenge["failure_state_bounded"]
            )

        episodes = self.chaos.rows() if chaotic else []
        report = ScenarioReport(
            name=sc.name,
            seed=sc.seed,
            scale=sc.scale,
            mode=(
                "tailer" if self.via_tailer
                else "kafka" if self.kafka_broker is not None else "direct"
            ),
            single_kernel=self.single_kernel,
            n_lines=n_lines,
            n_commands=n_cmds,
            feed_s=round(feed_s, 4),
            lines_per_sec=round(n_lines / feed_s, 1),
            shed_lines=shed,
            drain_error_lines=drain_err,
            stale_lines=stale,
            shed_ratio=round((shed + drain_err) / max(1, admitted), 6),
            fallback_batches=delta("PipelineFallbackBatches"),
            engine_bans=len(engine_bans),
            oracle_bans=len(oracle_bans),
            true_positives=tp,
            precision=round(precision, 6),
            recall=round(recall, 6),
            # the derived-budget input (3x p99, floor 50 ms): hostile-
            # shape device p99, banked so the chip round can set
            # matcher_latency_budget_ms from episode data
            device_p99_ms=peek.get("PipelineDeviceP99Ms"),
            slo_burn_peak={k: round(v, 4) for k, v in sorted(peaks.items())},
            slo_breached=self.slo.breached(),
            invariants=invariants,
            episodes=episodes,
            incidents=(
                self.flightrec.incident_count if self.flightrec else 0
            ),
            command_items=self._commands_handled,
            challenge=challenge,
        )
        scen_stats.get_stats().note_run(
            sc.name,
            {
                "lines_per_sec": report.lines_per_sec,
                "shed_ratio": report.shed_ratio,
                "precision": report.precision,
                "recall": report.recall,
                "slo_burn_peak": max(peaks.values()) if peaks else 0.0,
            },
            episodes=len(episodes),
            invariant_failures=sum(
                1 for v in invariants.values() if not v
            ),
        )
        return report
