"""Adversarial traffic-scenario harness (ROADMAP item 4).

Every bench before this package replayed one uniform tailer-shaped feed;
the reference's real workload is hostile — rotating-proxy botnets, slow
drips under many user agents, Baskerville command floods, challenge
storms, log rotation mid-burst.  This package turns those shapes into
deterministic, oracle-checked evidence:

  * shapes.py   — named attack-shape generators.  Same seed → byte-
                  identical line stream + identical ground-truth oracle.
  * oracle.py   — an independent reference-semantics simulator (fixed
                  windows with the Go quirks) producing the expected
                  (ip, rule) ban multiset for any line stream.
  * runtime.py  — ScenarioRunner: feeds a scenario through the real
                  engine (TpuMatcher + PipelineScheduler, device windows
                  on), measures lines/s, shed ratio, ban precision/recall
                  vs the oracle and SLO burn peaks, and asserts the
                  structural invariants (admitted == processed + shed,
                  zero leaked fused turns/pins, benign ⇒ no SLO breach).
  * chaos.py    — seeded chaos schedules arming resilience/failpoints.py
                  points mid-stream, one flight-recorder bundle per
                  injected episode.
  * stats.py    — last-run summary the /metrics exposition renders as
                  the banjax_scenario_* families.

Entry points: `bench.py --scenarios` banks one row per shape into
BENCH_scenarios.json; `tests/soak/` runs a short seeded chaos pass in
tier-1 and a long one behind `-m slow`.
"""

from banjax_tpu.scenarios.chaos import ChaosSchedule  # noqa: F401
from banjax_tpu.scenarios.oracle import expected_bans  # noqa: F401
from banjax_tpu.scenarios.runtime import ScenarioRunner  # noqa: F401
from banjax_tpu.scenarios.shapes import (  # noqa: F401
    SHAPES,
    CommandBatch,
    LineChunk,
    Rotation,
    Scenario,
    generate,
)
