"""Scenario-harness run summary for the /metrics exposition.

A LEAF module (stdlib only): obs/exposition.py imports it lazily inside
render_prometheus, so a scrape on a process that never ran a scenario
pays one import and one lock — and declaring the banjax_scenario_*
families in obs/registry.py keeps the schema CI-locked like every other
surface.  ScenarioRunner publishes here after every run; totals are
process-lifetime counters, per-scenario gauges are last-run values.
"""

from __future__ import annotations

import threading
from typing import Dict


class ScenarioStats:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.runs_total = 0
        self.episodes_total = 0
        self.invariant_failures_total = 0
        # scenario name -> {lines_per_sec, shed_ratio, precision, recall,
        #                   slo_burn_peak}
        self._last: Dict[str, Dict[str, float]] = {}

    def note_run(self, name: str, row: Dict[str, float],
                 episodes: int = 0, invariant_failures: int = 0) -> None:
        with self._lock:
            self.runs_total += 1
            self.episodes_total += episodes
            self.invariant_failures_total += invariant_failures
            self._last[name] = dict(row)

    def prom_snapshot(self) -> dict:
        with self._lock:
            return {
                "runs_total": self.runs_total,
                "episodes_total": self.episodes_total,
                "invariant_failures_total": self.invariant_failures_total,
                "scenarios": {k: dict(v) for k, v in self._last.items()},
            }

    def reset(self) -> None:
        """Test isolation only."""
        with self._lock:
            self.runs_total = 0
            self.episodes_total = 0
            self.invariant_failures_total = 0
            self._last.clear()


_stats = ScenarioStats()


def get_stats() -> ScenarioStats:
    return _stats
