"""Dynamic (runtime) expiring decision lists.

Reference behavior: /root/reference/internal/decision.go:379-604 — two
mutex-protected maps (ip → ExpiringDecision, session_id → ExpiringDecision)
with: monotonic-severity updates (a new decision ≤ the existing one is a
no-op), lazy expiry on read, a 9-second background sweep, per-domain listing
for the /banned API, and Clear() on hot reload.

This host-side dict stays the single source of truth for Decisions (the
acceptance bar is byte-identical Decision output); the TPU matcher produces
*candidate* decisions that are merged through the same `update()` below.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple

from banjax_tpu.decisions.model import Decision
from banjax_tpu.obs import provenance

SWEEP_INTERVAL_SECONDS = 9  # decision.go:396


@dataclasses.dataclass
class ExpiringDecision:
    """decision.go:60-66."""

    decision: Decision
    expires: float  # unix seconds
    ip_address: str
    from_baskerville: bool
    domain: str


@dataclasses.dataclass
class BannedEntry:
    """Entry of the /banned API response (config.go:133-148)."""

    ip_or_session_id: str
    domain: str
    decision: str
    expires: float
    from_baskerville: bool


class DynamicDecisionLists:
    def __init__(self, start_sweeper: bool = True):
        self._lock = threading.Lock()
        self._by_ip: Dict[str, ExpiringDecision] = {}
        self._by_session_id: Dict[str, ExpiringDecision] = {}
        self._mirror = None  # native decision table (set_mirror)
        self._stop = threading.Event()
        if start_sweeper:
            t = threading.Thread(target=self._sweep_loop, name="dynamic-lists-sweeper", daemon=True)
            t.start()

    def close(self) -> None:
        self._stop.set()

    def set_mirror(self, table) -> None:
        """Attach the native decision table (native/decisiontable.py):
        every mutation below is mirrored into it UNDER this list's lock,
        so the serving fast path and this dict move together.  Only the
        authoritative instance mirrors (the primary's list; worker
        replicas attach the shm segment read-only) — a replica mirroring
        too would double-apply every broadcast insert."""
        with self._lock:
            self._mirror = table

    # The mirror is an accelerator, never an authority: any table error
    # degrades to "the fast path misses and the chain serves it", so
    # mirror calls swallow everything (counted by the serving stats).
    def _mirror_put(self, ed: ExpiringDecision) -> None:
        if self._mirror is None:
            return
        try:
            self._mirror.put(
                ed.ip_address, int(ed.decision), ed.expires,
                ed.from_baskerville, ed.domain,
            )
        except Exception:  # noqa: BLE001
            self._note_mirror_error()

    def _mirror_del(self, ip: str) -> None:
        if self._mirror is None:
            return
        try:
            self._mirror.delete(ip)
        except Exception:  # noqa: BLE001
            self._note_mirror_error()

    def _mirror_session(self, delta: int) -> None:
        if self._mirror is None:
            return
        try:
            self._mirror.session_add(delta)
        except Exception:  # noqa: BLE001
            self._note_mirror_error()

    @staticmethod
    def _note_mirror_error() -> None:
        try:
            from banjax_tpu.httpapi.serve_stats import get_stats

            get_stats().note_mirror_error()
        except Exception:  # noqa: BLE001
            pass

    def update(
        self,
        ip: str,
        expires: float,
        new_decision: Decision,
        from_baskerville: bool,
        domain: str,
    ) -> None:
        """Monotonic-severity insert (decision.go:404-439)."""
        with self._lock:
            existing = self._by_ip.get(ip)
            if existing is not None and new_decision <= existing.decision:
                return
            ed = ExpiringDecision(
                new_decision, expires, ip, from_baskerville, domain
            )
            self._by_ip[ip] = ed
            self._mirror_put(ed)

    def update_by_session_id(
        self,
        ip: str,
        session_id: str,
        expires: float,
        new_decision: Decision,
        from_baskerville: bool,
        domain: str,
    ) -> None:
        """decision.go:441-472."""
        with self._lock:
            existing = self._by_session_id.get(session_id)
            if existing is not None and new_decision <= existing.decision:
                return
            self._by_session_id[session_id] = ExpiringDecision(
                new_decision, expires, ip, from_baskerville, domain
            )
            if existing is None:
                # the fast path only needs to KNOW session entries exist
                # (its session guard defers any cookie-bearing request to
                # the chain); a count is enough, no session keys in shm
                self._mirror_session(1)

    def check(self, session_id: str, client_ip: str) -> Tuple[Optional[ExpiringDecision], bool]:
        """Session id first, then IP; lazy expiry on read (decision.go:474-500).

        Quirk preserved: a *found-but-expired* session entry returns
        (entry, False) without falling through to the IP lookup, exactly as
        the Go early-return at decision.go:487 does.
        """
        now = time.time()
        with self._lock:
            if session_id:
                ed = self._by_session_id.get(session_id)
                if ed is not None:
                    if now - ed.expires > 0:
                        del self._by_session_id[session_id]
                        self._mirror_session(-1)
                        provenance.record(
                            provenance.SOURCE_EXPIRY, ed.ip_address,
                            ed.decision, rule="session-lazy",
                        )
                        return ed, False
                    return ed, True
            ed = self._by_ip.get(client_ip)
            if ed is not None:
                if now - ed.expires > 0:
                    del self._by_ip[client_ip]
                    self._mirror_del(client_ip)
                    provenance.record(
                        provenance.SOURCE_EXPIRY, client_ip, ed.decision,
                        rule="lazy",
                    )
                    return ed, False
                return ed, True
        return None, False

    def peek(self, ip: str) -> Optional[ExpiringDecision]:
        """Read-only lookup for introspection (/decisions/explain): no
        lazy-expiry side effect — an admin read must not mutate the list
        (check() deletes expired entries and records their expiry)."""
        with self._lock:
            return self._by_ip.get(ip)

    def check_by_domain(self, domain: str) -> List[BannedEntry]:
        """decision.go:502-530 — entries with severity ≥ Challenge for a domain."""
        out: List[BannedEntry] = []
        with self._lock:
            for ip, ed in self._by_ip.items():
                if ed.domain == domain and ed.decision >= Decision.CHALLENGE:
                    out.append(BannedEntry(ip, ed.domain, str(ed.decision), ed.expires, ed.from_baskerville))
            for sid, ed in self._by_session_id.items():
                if ed.domain == domain and ed.decision >= Decision.CHALLENGE:
                    out.append(BannedEntry(sid, ed.domain, str(ed.decision), ed.expires, ed.from_baskerville))
        return out

    def remove_by_ip(self, ip: str) -> None:
        with self._lock:
            self._by_ip.pop(ip, None)
            self._mirror_del(ip)

    def clear(self) -> None:
        with self._lock:
            self._by_ip.clear()
            self._by_session_id.clear()
            if self._mirror is not None:
                try:
                    self._mirror.clear()
                except Exception:  # noqa: BLE001
                    self._note_mirror_error()

    def metrics(self) -> Tuple[int, int]:
        """(len_expiring_challenges, len_expiring_blocks) — decision.go:548-564."""
        challenges = 0
        blocks = 0
        with self._lock:
            for ed in self._by_ip.values():
                if ed.decision == Decision.CHALLENGE:
                    challenges += 1
                elif ed.decision in (Decision.NGINX_BLOCK, Decision.IPTABLES_BLOCK):
                    blocks += 1
        return challenges, blocks

    def format_ip_entries(self) -> Dict[str, ExpiringDecision]:
        with self._lock:
            return dict(self._by_ip)

    def _remove_expired(self) -> None:
        now = time.time()
        with self._lock:
            for ip in [ip for ip, ed in self._by_ip.items() if now - ed.expires > 0]:
                ed = self._by_ip.pop(ip)
                self._mirror_del(ip)
                provenance.record(
                    provenance.SOURCE_EXPIRY, ip, ed.decision, rule="sweep"
                )

    def _sweep_loop(self) -> None:
        while not self._stop.wait(SWEEP_INTERVAL_SECONDS):
            self._remove_expired()
