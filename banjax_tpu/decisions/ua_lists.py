"""User-Agent decision lists.

Reference behavior: /root/reference/internal/user_agent_decision.go:17-96 —
each configured pattern is auto-detected as a regex (if it contains any of the
metacharacters ``\\.+*?[]{}()|^$``) or a plain substring; regexes pre-compile
at config load (bad ones fail the load). Matching iterates decisions in
severity order IptablesBlock → NginxBlock → Challenge → Allow; first matching
pattern wins.

The same patterns also feed the fused UA+path TPU matching config
(BASELINE.json configs[3]) via banjax_tpu/matcher/rulec.py.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from banjax_tpu.decisions.model import Decision, parse_decision
from banjax_tpu.matcher.re2check import check_re2_compatible

_METACHARS = set("\\.+*?[]{}()|^$")

# Severity order checked by check_ua_decision (user_agent_decision.go:56).
_UA_CHECK_ORDER = (
    Decision.IPTABLES_BLOCK,
    Decision.NGINX_BLOCK,
    Decision.CHALLENGE,
    Decision.ALLOW,
)


class UAPattern:
    """A pre-compiled optional regex alongside the raw pattern string."""

    __slots__ = ("raw", "compiled")

    def __init__(self, raw: str):
        self.raw = raw
        if contains_regex_metachar(raw):
            check_re2_compatible(raw)
            try:
                self.compiled: Optional["re.Pattern[str]"] = re.compile(raw)
            except re.error as e:
                raise ValueError(f"invalid UA regex pattern {raw!r}: {e}") from None
        else:
            self.compiled = None

    def matches(self, user_agent: str) -> bool:
        if self.compiled is not None:
            return self.compiled.search(user_agent) is not None
        return self.raw in user_agent


def contains_regex_metachar(s: str) -> bool:
    return any(ch in _METACHARS for ch in s)


UARules = Dict[Decision, List[UAPattern]]


def check_ua_decision(rules: UARules, user_agent: str) -> Tuple[Optional[Decision], bool]:
    """First match in severity order wins (user_agent_decision.go:55-64)."""
    for d in _UA_CHECK_ORDER:
        for p in rules.get(d, ()):
            if p.matches(user_agent):
                return d, True
    return None, False


def build_ua_rules(raw: Dict[str, List[str]]) -> UARules:
    """decision-string → patterns, from a config map (user_agent_decision.go:67-83)."""
    out: UARules = {}
    for decision_string, patterns in raw.items():
        decision = parse_decision(decision_string)
        for raw_pattern in patterns or []:
            out.setdefault(decision, []).append(UAPattern(raw_pattern))
    return out


def build_per_site_ua_rules(
    raw: Dict[str, Dict[str, List[str]]],
) -> Dict[str, UARules]:
    out: Dict[str, UARules] = {}
    for site, decision_to_patterns in raw.items():
        try:
            out[site] = build_ua_rules(decision_to_patterns)
        except ValueError as e:
            raise ValueError(f"per_site_user_agent_decision_lists[{site}]: {e}") from None
    return out
