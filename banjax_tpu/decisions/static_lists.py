"""Static (config-sourced) decision lists.

Reference behavior: /root/reference/internal/decision.go:88-374 — an
immutable snapshot of per-site and global IP→Decision maps. Plain IPs go into
exact-match dicts; every list (plain IPs AND CIDRs) also populates one filter
per decision, checked in the fixed order Allow → Challenge → NginxBlock →
IptablesBlock (first filter containing the IP wins). The snapshot also holds
the sitewide SHA-inv site→FailAction map and the UA pattern lists.

`check_is_allowed` is the allowlist exemption used by the log tailer
(decision.go:185-216); in the TPU matcher the same allowlist is materialized
as a device-side mask over (ip, host) pairs before the window counters run.
"""

from __future__ import annotations

import ipaddress
import socket
from typing import Dict, List, Optional, Tuple

from banjax_tpu.config.schema import Config
from banjax_tpu.decisions.model import Decision, FailAction, parse_decision, parse_fail_action
from banjax_tpu.decisions.ua_lists import (
    UARules,
    build_per_site_ua_rules,
    build_ua_rules,
    check_ua_decision,
)

# The iteration order of per-decision CIDR filters (decision.go:127,149).
_FILTER_CHECK_ORDER = (
    Decision.ALLOW,
    Decision.CHALLENGE,
    Decision.NGINX_BLOCK,
    Decision.IPTABLES_BLOCK,
)


def _fast_parse_ip(ip_string: str) -> Optional[Tuple[int, int]]:
    """(version, address-int) via inet_pton — ~15x faster than the
    ipaddress module on the request hot path, with identical accept/reject
    behavior for unscoped addresses (leading zeros, short forms, stray
    whitespace and out-of-range octets all rejected the same way).  Scoped
    IPv6 ("%zone", which ipaddress accepts but inet_pton rejects) returns
    None so callers take the slow exact-semantics path."""
    # OSError: not parseable; ValueError: embedded NUL / non-str input.
    # byteorder is explicit: it only defaults to 'big' on Python >= 3.11,
    # and this parser must work on 3.10 too.
    try:
        return 4, int.from_bytes(socket.inet_pton(socket.AF_INET, ip_string), "big")
    except (OSError, ValueError):
        pass
    try:
        return 6, int.from_bytes(socket.inet_pton(socket.AF_INET6, ip_string), "big")
    except (OSError, ValueError):
        return None


class IPFilter:
    """Membership test over a mixed list of plain IPs and CIDR blocks.

    Equivalent of the reference's per-decision `ipfilter` instance
    (decision.go:300-303): the filter is built from the FULL list for a
    decision — plain IPs included — so a plain-IP entry also matches here.
    Unparseable entries are skipped (ipfilter tolerates them silently).

    Membership runs on plain ints (version, address) parsed with
    inet_pton; build time keeps the ipaddress module (entries are
    config-sourced and may use forms inet_pton rejects, e.g. host bits
    set on a CIDR).
    """

    __slots__ = ("_singles", "_networks", "_slow_singles", "_slow_networks")

    def __init__(self, entries: List[str]):
        self._singles = set()  # (version, int) — unscoped entries only
        self._networks: List[Tuple[int, int, int]] = []  # (version, net, mask)
        self._slow_singles = set()  # ipaddress objects (original semantics)
        self._slow_networks = []
        for entry in entries:
            entry = entry.strip()
            if not entry:
                continue
            try:
                if "/" in entry:
                    net = ipaddress.ip_network(entry, strict=False)
                    self._slow_networks.append(net)
                    self._networks.append((
                        net.version,
                        int(net.network_address),
                        int(net.netmask),
                    ))
                else:
                    addr = ipaddress.ip_address(entry)
                    self._slow_singles.add(addr)
                    if getattr(addr, "scope_id", None) is None:
                        # a scoped entry can never equal an unscoped input,
                        # and fast-path inputs are always unscoped
                        self._singles.add((addr.version, int(addr)))
            except ValueError:
                continue

    def allowed(self, ip_string: str) -> bool:
        parsed = _fast_parse_ip(ip_string)
        if parsed is None:
            return self._allowed_slow(ip_string)
        if parsed in self._singles:
            return True
        version, addr = parsed
        return any(
            v == version and (addr & mask) == net
            for v, net, mask in self._networks
        )

    def _allowed_slow(self, ip_string: str) -> bool:
        # inputs inet_pton cannot parse: either garbage (reject, like the
        # reference's ipfilter) or scoped IPv6, where the ipaddress module
        # defines the semantics
        try:
            addr = ipaddress.ip_address(ip_string)
        except ValueError:
            return False
        if addr in self._slow_singles:
            return True
        return any(addr in net for net in self._slow_networks)


class _Snapshot:
    """Immutable contents (decision.go:256-276)."""

    __slots__ = (
        "global_decision_lists",
        "per_site_decision_lists",
        "sitewide_sha_inv_list",
        "global_ip_filters",
        "per_site_ip_filters",
        "per_site_ua_rules",
        "global_ua_rules",
    )

    def __init__(self) -> None:
        self.global_decision_lists: Dict[str, Decision] = {}
        self.per_site_decision_lists: Dict[str, Dict[str, Decision]] = {}
        self.sitewide_sha_inv_list: Dict[str, FailAction] = {}
        self.global_ip_filters: Dict[Decision, IPFilter] = {}
        self.per_site_ip_filters: Dict[str, Dict[Decision, IPFilter]] = {}
        self.per_site_ua_rules: Dict[str, UARules] = {}
        self.global_ua_rules: UARules = {}


def _snapshot_from_config(config: Config) -> _Snapshot:
    """Port of newStaticDecisionListsFromConfig (decision.go:278-374)."""
    out = _Snapshot()

    for decision_string, ips in config.global_decision_lists.items():
        decision = parse_decision(decision_string)
        for ip in ips or []:
            if "/" not in ip:
                out.global_decision_lists[ip] = decision
        # filter is built from the full list, plain IPs included
        out.global_ip_filters[decision] = IPFilter(list(ips or []))

    for site, decision_to_ips in config.per_site_decision_lists.items():
        for decision_string, ips in decision_to_ips.items():
            decision = parse_decision(decision_string)
            for ip in ips or []:
                out.per_site_decision_lists.setdefault(site, {})
                out.per_site_ip_filters.setdefault(site, {})
                if "/" not in ip:
                    out.per_site_decision_lists[site][ip] = decision
            if ips:
                # decision.go:330-337: only init the filter for non-empty lists
                out.per_site_ip_filters.setdefault(site, {})[decision] = IPFilter(list(ips))

    for site, fail_action_string in config.sitewide_sha_inv_list.items():
        out.sitewide_sha_inv_list[site] = parse_fail_action(fail_action_string)

    if config.global_user_agent_decision_lists:
        out.global_ua_rules = build_ua_rules(config.global_user_agent_decision_lists)
    if config.per_site_user_agent_decision_lists:
        out.per_site_ua_rules = build_per_site_ua_rules(
            config.per_site_user_agent_decision_lists
        )

    return out


class StaticDecisionLists:
    """Atomically-swapped snapshot of config-sourced decisions."""

    def __init__(self, config: Config):
        self._snapshot = _snapshot_from_config(config)
        # public change counter: callers caching per-(host, ip) results
        # (TpuMatcher's allowlist cache) key on this and must discard on
        # any bump — never on identity of private internals
        self.generation = 0

    def update_from_config(self, config: Config) -> None:
        # Build fully, then swap — readers never see a partial snapshot.
        self._snapshot = _snapshot_from_config(config)
        self.generation += 1

    def check_per_site(self, site: str, client_ip: str) -> Tuple[Optional[Decision], bool]:
        """decision.go:115-139 — exact map first, then per-decision filters in order."""
        c = self._snapshot
        site_map = c.per_site_decision_lists.get(site)
        if site_map is not None and client_ip in site_map:
            return site_map[client_ip], True
        site_filters = c.per_site_ip_filters.get(site)
        if site_filters:
            for decision in _FILTER_CHECK_ORDER:
                f = site_filters.get(decision)
                if f is not None and f.allowed(client_ip):
                    return decision, True
        return None, False

    def check_global(self, client_ip: str) -> Tuple[Optional[Decision], bool]:
        """decision.go:141-162."""
        c = self._snapshot
        if client_ip in c.global_decision_lists:
            return c.global_decision_lists[client_ip], True
        for decision in _FILTER_CHECK_ORDER:
            f = c.global_ip_filters.get(decision)
            if f is not None and f.allowed(client_ip):
                return decision, True
        return None, False

    def check_per_site_user_agent(self, site: str, user_agent: str) -> Tuple[Optional[Decision], bool]:
        """decision.go:164-171."""
        rules = self._snapshot.per_site_ua_rules.get(site)
        if rules is None:
            return None, False
        return check_ua_decision(rules, user_agent)

    def check_global_user_agent(self, user_agent: str) -> Tuple[Optional[Decision], bool]:
        """decision.go:173-176."""
        return check_ua_decision(self._snapshot.global_ua_rules, user_agent)

    def check_sitewide_sha_inv(self, site: str) -> Tuple[Optional[FailAction], bool]:
        """decision.go:178-183."""
        fa = self._snapshot.sitewide_sha_inv_list.get(site)
        return fa, fa is not None

    def has_any_allow_entries(self) -> bool:
        """True when ANY allow source exists (exact or CIDR, global or any
        site). When False, check_is_allowed is False for every input — the
        matcher gate skips its per-distinct-(host, ip) loop entirely."""
        c = self._snapshot
        if any(d == Decision.ALLOW for d in c.global_decision_lists.values()):
            return True
        if Decision.ALLOW in c.global_ip_filters:
            return True
        for site_map in c.per_site_decision_lists.values():
            if any(d == Decision.ALLOW for d in site_map.values()):
                return True
        for filters in c.per_site_ip_filters.values():
            if Decision.ALLOW in filters:
                return True
        return False

    def check_is_allowed(self, site: str, client_ip: str) -> bool:
        """Allowlist exemption for the log tailer (decision.go:185-216)."""
        c = self._snapshot
        site_map = c.per_site_decision_lists.get(site)
        if site_map is not None and site_map.get(client_ip) == Decision.ALLOW:
            return True
        site_filters = c.per_site_ip_filters.get(site)
        if site_filters:
            f = site_filters.get(Decision.ALLOW)
            if f is not None and f.allowed(client_ip):
                return True
        if c.global_decision_lists.get(client_ip) == Decision.ALLOW:
            return True
        f = c.global_ip_filters.get(Decision.ALLOW)
        if f is not None and f.allowed(client_ip):
            return True
        return False

    # for /decision_lists formatting
    def format_lists(self) -> Tuple[Dict[str, Dict[str, Decision]], Dict[str, Decision]]:
        c = self._snapshot
        return c.per_site_decision_lists, c.global_decision_lists
