"""Password-protected path classification.

Reference behavior: /root/reference/internal/password_protected_path.go —
an immutable snapshot of site→protected-path-prefixes, site→exceptions
(exact-path match), site→password-hash (hex-decoded sha256), roaming hashes
(a subdomain inherits its root site's hash, which flips the root's
expand-cookie-domain flag), and the ClassifyPath rule: an exact exception
beats a prefix-protected path.
"""

from __future__ import annotations

import enum
from typing import Dict, Optional, Tuple

from banjax_tpu.config.schema import Config


class PathType(enum.IntEnum):
    NOT_PASSWORD_PROTECTED = 0
    PASSWORD_PROTECTED = 1
    PASSWORD_PROTECTED_EXCEPTION = 2


def _normalize(path: str) -> str:
    # password_protected_path.go:134 — "/" + strings.Trim(path, "/")
    return "/" + path.strip("/")


class _Snapshot:
    __slots__ = (
        "site_to_protected_paths",
        "site_to_exceptions",
        "site_to_password_hash",
        "site_to_roaming_password_hash",
        "site_to_expand_cookie_domain",
    )

    def __init__(self, config: Config):
        self.site_to_protected_paths: Dict[str, Dict[str, bool]] = {}
        self.site_to_exceptions: Dict[str, Dict[str, bool]] = {}
        self.site_to_password_hash: Dict[str, bytes] = {}
        self.site_to_roaming_password_hash: Dict[str, bytes] = {}
        self.site_to_expand_cookie_domain: Dict[str, bool] = {}

        for site, paths in config.password_protected_paths.items():
            for path in paths or []:
                self.site_to_protected_paths.setdefault(site, {})[_normalize(path)] = True

        for site, exceptions in config.password_protected_path_exceptions.items():
            for exc in exceptions or []:
                self.site_to_exceptions.setdefault(site, {})[_normalize(exc)] = True

        for site, hash_hex in config.password_hashes.items():
            try:
                self.site_to_password_hash[site] = bytes.fromhex(hash_hex)
            except ValueError:
                raise ValueError(f"bad password hash: {hash_hex!r}") from None

        for site, root_site in config.password_hash_roaming.items():
            # password_protected_path.go:169-177 — only if the root has a hash
            root_hash = self.site_to_password_hash.get(root_site)
            if root_hash is not None:
                self.site_to_roaming_password_hash[site] = root_hash
                self.site_to_expand_cookie_domain[root_site] = True


class PasswordProtectedPaths:
    def __init__(self, config: Config):
        self._snapshot = _Snapshot(config)

    def update_from_config(self, config: Config) -> None:
        self._snapshot = _Snapshot(config)

    def get_password_hash(self, site: str) -> Tuple[Optional[bytes], bool]:
        v = self._snapshot.site_to_password_hash.get(site)
        return v, v is not None

    def get_roaming_password_hash(self, site: str) -> Tuple[Optional[bytes], bool]:
        v = self._snapshot.site_to_roaming_password_hash.get(site)
        return v, v is not None

    def get_expand_cookie_domain(self, site: str) -> Tuple[bool, bool]:
        c = self._snapshot.site_to_expand_cookie_domain
        return c.get(site, False), site in c

    def is_exception(self, site: str, path: str) -> bool:
        """Exact match against the exception set (password_protected_path.go:61-70)."""
        exceptions = self._snapshot.site_to_exceptions.get(site)
        return bool(exceptions and exceptions.get(path))

    def classify_path(self, site: str, path: str) -> PathType:
        """password_protected_path.go:72-90 — exception (exact) beats protected (prefix)."""
        c = self._snapshot
        path_map = c.site_to_protected_paths.get(site)
        if path_map is not None:
            exceptions = c.site_to_exceptions.get(site)
            if not exceptions or not exceptions.get(path):
                for protected_path, flag in path_map.items():
                    if flag and path.startswith(protected_path):
                        return PathType.PASSWORD_PROTECTED
            else:
                return PathType.PASSWORD_PROTECTED_EXCEPTION
        return PathType.NOT_PASSWORD_PROTECTED
