"""Fixed-window rate-limit states (host-side, Go-semantics reference).

Reference behavior: /root/reference/internal/rate_limit.go — per-IP per-rule
fixed-window counters with three quirks that are part of the contract:

  * the window restarts (hits := 1) when `timestamp - start > interval`
    (strictly greater, in nanoseconds);
  * on exceed (`hits > hits_per_interval`, strictly greater) the hit count
    resets to 0 — not 1 (the reference's own "XXX should it be 1?" comment at
    rate_limit.go:71);
  * a brand-new IP reports seen_ip=False and MatchType FirstTime semantics.

Timestamps are carried as integer nanoseconds to mirror Go's time.Time
comparison exactly. The TPU matcher (banjax_tpu/matcher/windows.py)
re-implements these exact transitions as a segmented scan and is
differential-tested against this class.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
import time
from typing import Dict, Tuple

from banjax_tpu.config.schema import Config, RegexWithRate


class RateLimitMatchType(enum.IntEnum):
    FIRST_TIME = 0
    OUTSIDE_INTERVAL = 1
    INSIDE_INTERVAL = 2

    def __str__(self) -> str:
        return {
            RateLimitMatchType.FIRST_TIME: "FirstTime",
            RateLimitMatchType.OUTSIDE_INTERVAL: "OutsideInterval",
            RateLimitMatchType.INSIDE_INTERVAL: "InsideInterval",
        }[self]


@dataclasses.dataclass
class RateLimitResult:
    match_type: RateLimitMatchType = RateLimitMatchType.FIRST_TIME
    exceeded: bool = False


@dataclasses.dataclass
class NumHitsAndIntervalStart:
    num_hits: int
    interval_start_time_ns: int


class RegexRateLimitStates:
    """ip → rule-name → (num_hits, interval_start) — rate_limit.go:18-103."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._states: Dict[str, Dict[str, NumHitsAndIntervalStart]] = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._states)

    def apply(
        self, ip: str, rule: RegexWithRate, timestamp_ns: int
    ) -> Tuple[bool, RateLimitResult]:
        """Port of RegexRateLimitStates.Apply (rate_limit.go:37-78)."""
        result = RateLimitResult()
        with self._lock:
            states = self._states.get(ip)
            if states is None:
                seen_ip = False
                state = NumHitsAndIntervalStart(1, timestamp_ns)
                self._states[ip] = {rule.rule: state}
            else:
                seen_ip = True
                state = states.get(rule.rule)
                if state is not None:
                    if timestamp_ns - state.interval_start_time_ns > rule.interval_ns:
                        result.match_type = RateLimitMatchType.OUTSIDE_INTERVAL
                        state.num_hits = 1
                        state.interval_start_time_ns = timestamp_ns
                    else:
                        result.match_type = RateLimitMatchType.INSIDE_INTERVAL
                        state.num_hits += 1
                else:
                    result.match_type = RateLimitMatchType.FIRST_TIME
                    state = NumHitsAndIntervalStart(1, timestamp_ns)
                    states[rule.rule] = state

            if state.num_hits > rule.hits_per_interval:
                state.num_hits = 0  # reference quirk: reset to 0, not 1
                result.exceeded = True
            else:
                result.exceeded = False

        return seen_ip, result

    def get(self, ip: str) -> Tuple[Dict[str, NumHitsAndIntervalStart], bool]:
        """Deep copy for the given IP (rate_limit.go:81-96)."""
        with self._lock:
            states = self._states.get(ip)
            if states is None:
                return {}, False
            return {
                rule: NumHitsAndIntervalStart(s.num_hits, s.interval_start_time_ns)
                for rule, s in states.items()
            }, True

    def format_states(self) -> str:
        with self._lock:
            lines = []
            for ip, states in self._states.items():
                lines.append(f"{ip}:")
                for rule, s in states.items():
                    lines.append(f"\t{rule}:")
                    lines.append(
                        f"\t\tNumHitsAndIntervalStart({s.num_hits}, {s.interval_start_time_ns})"
                    )
                lines.append("")
            return "\n".join(lines) + ("\n" if lines else "")


class FailedChallengeRateLimitStates:
    """ip → (num_hits, interval_start) keyed by wall clock —
    rate_limit.go:106-163. Stays host-side (request path, low volume)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._states: Dict[str, NumHitsAndIntervalStart] = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._states)

    def apply(self, ip: str, config: Config) -> RateLimitResult:
        """Port of FailedChallengeRateLimitStates.Apply (rate_limit.go:125-156)."""
        result = RateLimitResult()
        timestamp_ns = time.time_ns()
        interval_ns = config.too_many_failed_challenges_interval_seconds * 1_000_000_000
        with self._lock:
            state = self._states.get(ip)
            if state is not None:
                if timestamp_ns - state.interval_start_time_ns > interval_ns:
                    result.match_type = RateLimitMatchType.OUTSIDE_INTERVAL
                    state.num_hits = 1
                    state.interval_start_time_ns = timestamp_ns
                else:
                    result.match_type = RateLimitMatchType.INSIDE_INTERVAL
                    state.num_hits += 1
            else:
                result.match_type = RateLimitMatchType.FIRST_TIME
                state = NumHitsAndIntervalStart(1, timestamp_ns)
                self._states[ip] = state

            if state.num_hits > config.too_many_failed_challenges_threshold:
                state.num_hits = 0  # same reference quirk
                result.exceeded = True
            else:
                result.exceeded = False

        return result

    def format_states(self) -> str:
        with self._lock:
            return "".join(
                f"{ip},: interval_start: {s.interval_start_time_ns}, num hits: {s.num_hits}\n"
                for ip, s in self._states.items()
            )
