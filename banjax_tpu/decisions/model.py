"""Decision model: the four-valued verdict every subsystem speaks.

Reference behavior: /root/reference/internal/decision.go:20-85 — an ordered
enum Allow < Challenge < NginxBlock < IptablesBlock whose ordering implements
the "never downgrade severity" rule used by the dynamic decision lists, plus
a two-valued FailAction used by the sitewide SHA-inv challenge list.

TPU note: the integer severity ordering is deliberate — on the device side the
decision merge becomes a `jnp.maximum` over int32 lanes (see
banjax_tpu/matcher/windows.py), so the enum values here are the on-device
encoding as well.
"""

from __future__ import annotations

import enum


class Decision(enum.IntEnum):
    """Severity-ordered verdict. 0 is reserved as "no decision" on device."""

    ALLOW = 1
    CHALLENGE = 2
    NGINX_BLOCK = 3
    IPTABLES_BLOCK = 4

    def __str__(self) -> str:  # matches decision.go:45-58 String()
        return _DECISION_TO_STRING[self]


_DECISION_TO_STRING = {
    Decision.ALLOW: "Allow",
    Decision.CHALLENGE: "Challenge",
    Decision.NGINX_BLOCK: "NginxBlock",
    Decision.IPTABLES_BLOCK: "IptablesBlock",
}

_STRING_TO_DECISION = {
    "allow": Decision.ALLOW,
    "challenge": Decision.CHALLENGE,
    "nginx_block": Decision.NGINX_BLOCK,
    "iptables_block": Decision.IPTABLES_BLOCK,
}


def parse_decision(s: str) -> Decision:
    """Parse a config-file decision string (decision.go:30-43)."""
    try:
        return _STRING_TO_DECISION[s]
    except KeyError:
        raise ValueError(f"invalid decision: {s}") from None


class FailAction(enum.IntEnum):
    """What a sitewide SHA-inv challenge does on repeated failure
    (decision.go:68-85)."""

    BLOCK = 1
    NO_BLOCK = 2


_STRING_TO_FAIL_ACTION = {
    "block": FailAction.BLOCK,
    "no_block": FailAction.NO_BLOCK,
}


def parse_fail_action(s: str) -> FailAction:
    try:
        return _STRING_TO_FAIL_ACTION[s]
    except KeyError:
        raise ValueError(f"invalid fail action: {s}") from None
