"""Challenge-cookie crypto: SHA-inverting proof-of-work and password cookies.

Reference behavior: /root/reference/internal/challenge_response.go — the
cookie format is base64(hmac[20] ‖ solution[32] ‖ expiry_unix_be[8]); the KDF
is sha256(secret); the MAC is HMAC-SHA1(derived_key, expiry_be8 ‖ binding)
where the binding is the client IP or the User-Agent (per
use_user_agent_in_cookie). PoW validity = count-leading-zero-bits(
sha256(hmac ‖ solution)) ≥ sha_inv_expected_zero_bits; password validity =
solution == sha256(hmac ‖ sha256(password)). Cookies must interoperate with
the unchanged client-side JS solvers, so every byte layout here is part of
the wire contract.
"""

from __future__ import annotations

import base64
import hashlib
import hmac as hmac_mod
import struct
import time
from typing import Tuple

from banjax_tpu.crypto._b64 import decode_cookie_b64

COOKIE_BYTE_LENGTH = 20 + 32 + 8


class CookieError(ValueError):
    pass


def compute_hmac(secret_key: str, expire_time_unix: int, client_binding: str) -> bytes:
    """challenge_response.go:23-35 — HMAC-SHA1(sha256(secret), expiry_be8 ‖ binding)."""
    derived_key = hashlib.sha256(secret_key.encode()).digest()
    expire_bytes = struct.pack(">Q", expire_time_unix & 0xFFFFFFFFFFFFFFFF)
    mac = hmac_mod.new(derived_key, digestmod=hashlib.sha1)
    mac.update(expire_bytes)
    mac.update(client_binding.encode())
    return mac.digest()


def count_zero_bits_from_left(data: bytes) -> int:
    """challenge_response.go:37-49 — leading-zero-bit count of the digest.

    O(1) big-int form of the reference's per-byte/per-bit loop: the
    leading-zero run is len*8 - bit_length of the value, identical to the
    loop for every byte pattern (tests/unit/test_challenge_crypto.py proves
    it exhaustively against the retained reference loop)."""
    value = int.from_bytes(data, "big")
    return len(data) * 8 - value.bit_length()


def parse_cookie(cookie_string: str) -> Tuple[bytes, bytes, bytes]:
    """Split a base64 cookie into (hmac, solution, expiration) —
    challenge_response.go:71-99, including the '+' → ' ' URL-unescape
    workaround for cookie values that crossed a query-unescaping proxy."""
    cookie_bytes = decode_cookie_b64(cookie_string, CookieError, "bad base64")

    if len(cookie_bytes) != COOKIE_BYTE_LENGTH:
        raise CookieError("bad length")

    return cookie_bytes[0:20], cookie_bytes[20:52], cookie_bytes[52:60]


def validate_expiration_and_hmac(
    secret_key: str,
    expiration_bytes: bytes,
    now_time_unix: float,
    hmac_from_client: bytes,
    client_binding: str,
) -> int:
    """challenge_response.go:51-69; returns the expiry unix time on success."""
    (expiration_int,) = struct.unpack(">Q", expiration_bytes)
    # float compare: Go compares with ns precision (challenge_response.go:59)
    if expiration_int < now_time_unix:
        raise CookieError(f"expiration time is in the past: {expiration_int}")
    expected = compute_hmac(secret_key, expiration_int, client_binding)
    if not hmac_mod.compare_digest(expected, hmac_from_client):
        raise CookieError("hmac not what it should be")
    return expiration_int


def validate_sha_inv_cookie(
    secret_key: str,
    cookie_string: str,
    now_time_unix: float,
    client_binding: str,
    expected_zero_bits: int,
) -> None:
    """challenge_response.go:101-131. Raises CookieError when invalid."""
    hmac_from_client, solution_bytes, expiration_bytes = parse_cookie(cookie_string)
    validate_expiration_and_hmac(
        secret_key, expiration_bytes, now_time_unix, hmac_from_client, client_binding
    )
    digest = hashlib.sha256(hmac_from_client + solution_bytes).digest()
    actual_zero_bits = count_zero_bits_from_left(digest)
    if actual_zero_bits < expected_zero_bits:
        raise CookieError(
            f"not enough zero bits in hash: expected {expected_zero_bits}, found {actual_zero_bits}"
        )


def validate_password_cookie(
    secret_key: str,
    cookie_string: str,
    now_time_unix: float,
    client_binding: str,
    hashed_password: bytes,
) -> None:
    """challenge_response.go:141-177 — solution must equal
    sha256(hmac ‖ sha256(password)). Raises CookieError when invalid."""
    hmac_from_client, solution_bytes, expiration_bytes = parse_cookie(cookie_string)
    validate_expiration_and_hmac(
        secret_key, expiration_bytes, now_time_unix, hmac_from_client, client_binding
    )
    expected_solution = hashlib.sha256(hmac_from_client + hashed_password).digest()
    if not hmac_mod.compare_digest(expected_solution, solution_bytes):
        raise CookieError("bad password")


def new_challenge_cookie_at(
    secret_key: str, expire_time_unix: int, client_binding: str
) -> str:
    """Deterministic issuance primitive: the cookie is a pure function of
    (secret, binding, expiry) — hmac[20] ‖ zeros[32] ‖ expiry_be8.  The
    stateless issuer (banjax_tpu/challenge/issuer.py) builds on this."""
    hmac_bytes = compute_hmac(secret_key, expire_time_unix, client_binding)
    cookie_bytes = (
        hmac_bytes[0:20] + b"\x00" * 32
        + struct.pack(">Q", expire_time_unix & 0xFFFFFFFFFFFFFFFF)
    )
    return base64.standard_b64encode(cookie_bytes).decode()


def new_challenge_cookie(secret_key: str, cookie_ttl_seconds: int, client_binding: str) -> str:
    """challenge_response.go:179-188 — hmac[20] ‖ zeros[32] ‖ expiry_be8."""
    expire_time = int(time.time()) + cookie_ttl_seconds
    return new_challenge_cookie_at(secret_key, expire_time, client_binding)


def solve_challenge_for_testing(cookie_string: str, zero_bits: int = 10) -> str:
    """Test-only PoW solver (challenge_response.go:190-215): brute-force an
    8-byte big-endian counter into bytes 44..52 until sha256(first 52 bytes)
    has ≥ zero_bits leading zero bits."""
    cookie_bytes = bytearray(base64.standard_b64decode(cookie_string))
    counter = 0
    while True:
        cookie_bytes[44:52] = struct.pack(">Q", counter)
        digest = hashlib.sha256(bytes(cookie_bytes[0:52])).digest()
        if count_zero_bits_from_left(digest) >= zero_bits:
            break
        counter += 1
    return base64.standard_b64encode(bytes(cookie_bytes)).decode()
