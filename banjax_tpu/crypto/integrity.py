"""Integrity check / bot score: scoring of the browser-fingerprint payload.

Reference behavior: /root/reference/internal/integrity_check.go — the client
JS on the challenge page stores a base64 JSON payload of 17 fingerprint
fields in the `deflect_integrity` cookie; the server decodes it and computes
a weighted 9-factor score normalized to [0,1] (webdriver=10, gpu_renderer=7,
no_plugins=3, zero_lang=3, low_cpu=2, low_memory=2, fullscreen=2,
color_depth=1, small_screen=1). A missing or invalid payload scores 1.0.
The sha256 fingerprint hash is over a fixed '|'-joined field string.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, List, Tuple

from banjax_tpu.crypto._b64 import decode_strict_b64

INTEGRITY_CHECK_COOKIE_NAME = "deflect_integrity"


def _json_field(d: Dict[str, Any], key: str, typ: type) -> Any:
    """Go encoding/json field semantics: absent or null → None (zero value
    kept by the caller); wrong JSON type → error. bool is not an int here,
    and a JSON float never unmarshals into a Go int field."""
    v = d.get(key)
    if v is None:
        return None
    if typ is int:
        if isinstance(v, bool) or not isinstance(v, int):
            raise ValueError(f"field {key}: cannot unmarshal {type(v).__name__} into int")
        return v
    if typ is bool and not isinstance(v, bool):
        raise ValueError(f"field {key}: cannot unmarshal {type(v).__name__} into bool")
    if not isinstance(v, typ):
        raise ValueError(f"field {key}: cannot unmarshal {type(v).__name__} into {typ.__name__}")
    return v

_FACTOR_WEIGHTS = {
    "webdriver": 10,
    "no_plugins": 3,
    "gpu_renderer": 7,
    "low_cpu": 2,
    "low_memory": 2,
    "color_depth": 1,
    "zero_lang": 3,
    "fullscreen": 2,
    "small_screen": 1,
}
_MAX_SCORE = sum(_FACTOR_WEIGHTS.values())

_SOFTWARE_RENDERERS = ("swiftshader", "llvmpipe", "mesa")


@dataclasses.dataclass
class IntegrityCheckPayload:
    """integrity_check.go:24-42; field names match the JSON keys."""

    webdriver: bool = False
    has_plugins: bool = False
    gpu_renderer: str = ""
    cpu: int = 0
    memory: int = 0
    screen_width: int = 0
    screen_height: int = 0
    window_inner_width: int = 0
    window_inner_height: int = 0
    color_depth: int = 0
    lang_length: int = 0
    language: str = ""
    languages: List[str] = dataclasses.field(default_factory=list)
    timezone: str = ""
    platform: str = ""
    canvas_fp: str = ""
    webgl_fp: str = ""
    math_fp: str = ""
    webcam: bool = False

    def to_json_dict(self) -> Dict[str, Any]:
        return {
            "webdriver": self.webdriver,
            "hasPlugins": self.has_plugins,
            "gpuRenderer": self.gpu_renderer,
            "cpu": self.cpu,
            "memory": self.memory,
            "screen": {"width": self.screen_width, "height": self.screen_height},
            "window": {"innerWidth": self.window_inner_width, "innerHeight": self.window_inner_height},
            "colorDepth": self.color_depth,
            "langLength": self.lang_length,
            "language": self.language,
            "languages": list(self.languages),
            "timezone": self.timezone,
            "platform": self.platform,
            "canvasFp": self.canvas_fp,
            "webglFp": self.webgl_fp,
            "mathFp": self.math_fp,
            "webcam": self.webcam,
        }

    @classmethod
    def from_json_dict(cls, d: Any) -> "IntegrityCheckPayload":
        """Strict decode matching Go encoding/json semantics: a JSON null
        (whole document or any field) is a no-op keeping the zero value; a
        type mismatch (string-into-bool, float-into-int, ...) is an error."""
        if d is None:
            return cls()
        if not isinstance(d, dict):
            raise ValueError("integrity payload must be a JSON object")
        screen = _json_field(d, "screen", dict) or {}
        window = _json_field(d, "window", dict) or {}
        languages_raw = _json_field(d, "languages", list) or []
        languages = []
        for x in languages_raw:
            if x is None:
                languages.append("")  # Go: null element → zero string
            elif isinstance(x, str):
                languages.append(x)
            else:
                raise ValueError("languages must be strings")
        return cls(
            webdriver=_json_field(d, "webdriver", bool) or False,
            has_plugins=_json_field(d, "hasPlugins", bool) or False,
            gpu_renderer=_json_field(d, "gpuRenderer", str) or "",
            cpu=_json_field(d, "cpu", int) or 0,
            memory=_json_field(d, "memory", int) or 0,
            screen_width=_json_field(screen, "width", int) or 0,
            screen_height=_json_field(screen, "height", int) or 0,
            window_inner_width=_json_field(window, "innerWidth", int) or 0,
            window_inner_height=_json_field(window, "innerHeight", int) or 0,
            color_depth=_json_field(d, "colorDepth", int) or 0,
            lang_length=_json_field(d, "langLength", int) or 0,
            language=_json_field(d, "language", str) or "",
            languages=languages,
            timezone=_json_field(d, "timezone", str) or "",
            platform=_json_field(d, "platform", str) or "",
            canvas_fp=_json_field(d, "canvasFp", str) or "",
            webgl_fp=_json_field(d, "webglFp", str) or "",
            math_fp=_json_field(d, "mathFp", str) or "",
            webcam=_json_field(d, "webcam", bool) or False,
        )


@dataclasses.dataclass
class IntegrityCheckPayloadWrapper:
    payload: IntegrityCheckPayload = dataclasses.field(default_factory=IntegrityCheckPayload)
    hash: str = ""


def _go_bool(b: bool) -> str:
    return "true" if b else "false"


def calc_fingerprint(p: IntegrityCheckPayload) -> str:
    """integrity_check.go:49-74 — sha256 over a '|'-joined field string.

    The Go format string ends with %t booleans; reproduce "true"/"false".
    """
    languages = ",".join(p.languages)
    raw = (
        f"{p.platform}|{p.timezone}|{p.language}|{languages}|{p.cpu}|{p.memory}|"
        f"{p.color_depth}|{p.lang_length}|{p.screen_width}x{p.screen_height}|"
        f"{p.gpu_renderer}|{p.canvas_fp}|{p.webgl_fp}|{p.math_fp}|"
        f"{_go_bool(p.webdriver)}|{_go_bool(p.has_plugins)}|{_go_bool(p.webcam)}"
    )
    return hashlib.sha256(raw.encode()).hexdigest()


def calc_bot_score(
    p: IntegrityCheckPayload,
) -> Tuple[float, str, IntegrityCheckPayloadWrapper]:
    """integrity_check.go:77-177 — returns (normalized score, top factor, wrapper)."""
    score = 0
    factor_scores: Dict[str, int] = {}

    def add(factor: str) -> None:
        nonlocal score
        score += _FACTOR_WEIGHTS[factor]
        factor_scores[factor] = _FACTOR_WEIGHTS[factor]

    if p.webdriver:
        add("webdriver")
    if not p.has_plugins:
        add("no_plugins")
    gpu_lower = p.gpu_renderer.lower()
    if any(s in gpu_lower for s in _SOFTWARE_RENDERERS):
        add("gpu_renderer")
    if p.cpu <= 2:
        add("low_cpu")
    if p.memory <= 2:
        add("low_memory")
    if p.color_depth < 24:
        add("color_depth")
    if p.lang_length == 0:
        add("zero_lang")
    if p.screen_width == p.window_inner_width and p.screen_height == p.window_inner_height:
        add("fullscreen")
    if p.screen_width < 1000 or p.screen_height < 700:
        add("small_screen")

    top_factor = ""
    top_score = 0
    for k, v in factor_scores.items():
        if v > top_score:
            top_score = v
            top_factor = k

    normalized = min(score / _MAX_SCORE, 1.0)
    return normalized, top_factor, IntegrityCheckPayloadWrapper(p, calc_fingerprint(p))


def calc_bot_score_from_cookie(
    base64_payload: str,
) -> Tuple[float, str, IntegrityCheckPayloadWrapper]:
    """integrity_check.go:179-197 — empty/invalid payloads score 1.0."""
    if not base64_payload:
        return 1.0, "no_payload", IntegrityCheckPayloadWrapper()
    try:
        decoded = decode_strict_b64(base64_payload)
        payload = IntegrityCheckPayload.from_json_dict(json.loads(decoded))
    except (ValueError, TypeError, AttributeError, json.JSONDecodeError):
        return 1.0, "err_payload", IntegrityCheckPayloadWrapper()
    return calc_bot_score(payload)
