"""Shared strict-base64 cookie decoding.

Go's base64.StdEncoding.DecodeString rejects any non-alphabet byte, which is
what makes the reference's '+'-mangled-to-' ' retry work
(challenge_response.go:75-84): the first decode FAILS on a space, then the
replace(' ', '+') retry succeeds. Python's default b64decode silently discards
non-alphabet bytes, so we must pass validate=True for the first attempt or
mangled cookies would decode to garbage instead of triggering the retry.
"""

from __future__ import annotations

import base64
from typing import Type


def decode_cookie_b64(cookie_string: str, error: Type[Exception], message: str) -> bytes:
    try:
        return base64.b64decode(cookie_string, validate=True)
    except (ValueError, TypeError):
        try:
            return base64.b64decode(cookie_string.replace(" ", "+"), validate=True)
        except (ValueError, TypeError):
            raise error(message) from None


def decode_strict_b64(payload: str) -> bytes:
    """Single-attempt strict decode (no space retry) — for payloads where the
    reference has no retry, e.g. the integrity cookie."""
    return base64.b64decode(payload, validate=True)
