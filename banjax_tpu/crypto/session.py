"""Session cookie: the 16-byte per-client session ID issued on every response.

Reference behavior: /root/reference/internal/session_cookie.go — cookie =
base64(hmac[4] ‖ random_id[4] ‖ expiry_unix_be[8]); the MAC is HMAC-SHA1(
sha256(secret), expiry_be8 ‖ client_ip ‖ id_be4) truncated to 4 bytes. The
session ID is the key the Kafka `*_session` commands target, and it is
surfaced to Nginx logs via X-Deflect-Session / X-Deflect-Session-New headers.
"""

from __future__ import annotations

import base64
import hashlib
import hmac as hmac_mod
import secrets
import struct
import time

from banjax_tpu.crypto._b64 import decode_cookie_b64

SESSION_COOKIE_NAME = "deflect_session"
EXPIRE_TIME_BYTE_LENGTH = 8
ID_BYTE_LENGTH = 4
HMAC_BYTE_LENGTH = 4
SESSION_ID_LENGTH = EXPIRE_TIME_BYTE_LENGTH + ID_BYTE_LENGTH + HMAC_BYTE_LENGTH


class SessionCookieError(ValueError):
    pass


# the sha256-derived key depends only on the config secret — memoized so
# the per-response cookie pays one HMAC, not HMAC + SHA256 (single entry:
# the secret changes only on config reload)
_derived_key_cache: tuple = ("", b"")


def _derived_key(secret_key: str) -> bytes:
    global _derived_key_cache
    cached_secret, cached = _derived_key_cache
    if cached_secret == secret_key:
        return cached
    key = hashlib.sha256(secret_key.encode()).digest()
    _derived_key_cache = (secret_key, key)
    return key


def _session_cookie_hmac(secret_key: str, expire_time_unix: int, client_ip: str, id_value: int) -> bytes:
    """session_cookie.go:40-55."""
    mac = hmac_mod.new(_derived_key(secret_key), digestmod=hashlib.sha1)
    mac.update(struct.pack(">Q", expire_time_unix & 0xFFFFFFFFFFFFFFFF))
    mac.update(client_ip.encode())
    mac.update(struct.pack(">I", id_value & 0xFFFFFFFF))
    return mac.digest()[0:HMAC_BYTE_LENGTH]


def new_session_cookie(secret_key: str, cookie_ttl_seconds: int, client_ip: str) -> str:
    """session_cookie.go:57-67."""
    expire_time = int(time.time()) + cookie_ttl_seconds
    id_value = secrets.randbits(32)
    hmac_bytes = _session_cookie_hmac(secret_key, expire_time, client_ip, id_value)
    cookie_bytes = (
        hmac_bytes
        + struct.pack(">I", id_value)
        + struct.pack(">Q", expire_time)
    )
    return base64.standard_b64encode(cookie_bytes).decode()


def validate_session_cookie(
    cookie_string: str, secret_key: str, now_time_unix: float, client_ip: str
) -> None:
    """session_cookie.go:69-104. Raises SessionCookieError when invalid."""
    cookie_bytes = decode_cookie_b64(
        cookie_string, SessionCookieError, "session cookie base64 decoding error"
    )

    if len(cookie_bytes) != SESSION_ID_LENGTH:
        raise SessionCookieError("bad session cookie length")

    hmac_from_client = cookie_bytes[0:HMAC_BYTE_LENGTH]
    id_bytes = cookie_bytes[HMAC_BYTE_LENGTH : HMAC_BYTE_LENGTH + ID_BYTE_LENGTH]
    expiration_bytes = cookie_bytes[HMAC_BYTE_LENGTH + ID_BYTE_LENGTH : SESSION_ID_LENGTH]

    (expiration_int,) = struct.unpack(">Q", expiration_bytes)
    if expiration_int < now_time_unix:
        raise SessionCookieError(f"session cookie expired: {expiration_int}")

    (id_value,) = struct.unpack(">I", id_bytes)
    expected = _session_cookie_hmac(secret_key, expiration_int, client_ip, id_value)
    if not hmac_mod.compare_digest(expected, hmac_from_client):
        raise SessionCookieError("hmac validation failed")
