"""banjax-tpu: TPU-native DDoS-mitigation decision engine (banjax-compatible)."""

__version__ = "0.1.0"
