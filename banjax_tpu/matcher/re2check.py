"""RE2-dialect compatibility check for configured regexes.

The reference compiles rules with Go's regexp package, which implements RE2:
no lookaround, no backreferences, guaranteed-linear matching. This framework
compiles patterns with Python `re` for the host path (a superset), so to keep
the two implementations accepting the same config files we reject the
Python-only constructs RE2 would refuse at load time
(reference: config.go:110 regexp.Compile failing the whole config load).

The TPU rule compiler (banjax_tpu/matcher/rulec.py) enforces the same subset
structurally — it simply has no way to express lookaround or backrefs in an
NFA transition tensor.
"""

from __future__ import annotations

import re

# Constructs Python re accepts but RE2 rejects.
_RE2_INCOMPATIBLE = re.compile(
    r"""
    \(\?=         # lookahead
  | \(\?!         # negative lookahead
  | \(\?<=        # lookbehind
  | \(\?<!        # negative lookbehind
  | \(\?\#        # comment group
  | \(\?P=        # named backreference
  | \(\?\(        # conditional group
  | \(\?>         # atomic group (Python >= 3.11)
  | [*+?]\+       # possessive quantifier *+ ++ ?+ (Python >= 3.11)
  | \{\d+(,\d*)?\}\+   # possessive {m,n}+ (a literal '}' before '+' is fine)
    """,
    re.VERBOSE,
)

_BACKREF = re.compile(r"\\[1-9]")


def check_re2_compatible(pattern: str) -> None:
    """Raise ValueError if `pattern` uses constructs RE2 (Go regexp) rejects.

    We scan the raw pattern text outside character classes; this is a
    conservative syntactic filter, not a full parser — rulec.py does the
    full parse for the device path.
    """
    # strip character classes and escaped chars before scanning for groups,
    # so that e.g. [(?=] or \( are not false positives
    stripped = _strip_classes_and_escapes(pattern)
    m = _RE2_INCOMPATIBLE.search(stripped)
    if m is not None:
        raise ValueError(
            f"regex {pattern!r} uses {m.group(0)!r}, which Go's RE2 engine does not support"
        )
    if _BACKREF.search(stripped):
        raise ValueError(
            f"regex {pattern!r} uses a backreference, which Go's RE2 engine does not support"
        )


def _strip_classes_and_escapes(pattern: str) -> str:
    # each class/escape is replaced by a placeholder atom (not dropped):
    # dropping would make the quantifiers of e.g. `\d+\.\d+` adjacent and
    # false-positive the possessive-quantifier scan as `++`
    out = []
    i = 0
    n = len(pattern)
    while i < n:
        c = pattern[i]
        if c == "\\" and i + 1 < n:
            nxt = pattern[i + 1]
            if nxt.isdigit():
                out.append(c)
                out.append(nxt)  # keep backrefs visible to the scanner
            else:
                out.append("x")
            i += 2
            continue
        if c == "[":
            # skip the whole class, honoring leading ^] and escapes
            i += 1
            if i < n and pattern[i] == "^":
                i += 1
            if i < n and pattern[i] == "]":
                i += 1
            while i < n and pattern[i] != "]":
                if pattern[i] == "\\":
                    i += 1
                i += 1
            i += 1  # closing ]
            out.append("x")
            continue
        out.append(c)
        i += 1
    return "".join(out)
