"""The Go-semantics CPU reference matcher.

Reference behavior: /root/reference/internal/regex_rate_limiter.go:113-269 —
the exact consumeLine/applyRegexToLog pipeline:

  * split "<epoch.frac> <ip> <rest>" (3 words minimum, else error);
  * parse the float timestamp (nanosecond precision via float64 multiply);
  * split rest into "<method> <host> <rest2>" (3 words minimum, else error);
  * drop lines older than 10 s against the wall clock (fail-safe);
  * skip IPs allowlisted for that host;
  * apply per-site rules for the host FIRST, then global rules;
  * per rule: regex over `rest` (unanchored search), hosts_to_skip check,
    fixed-window rate limit, and on exceed → BanOrChallengeIp + LogRegexBan.

This is the default matcher and the correctness oracle the TPU matcher is
differential-tested against.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from banjax_tpu.config.schema import Config, RegexWithRate
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.effectors.banner import BannerInterface
from banjax_tpu.matcher.api import ConsumeLineResult, Matcher, RuleResult
from banjax_tpu.matcher.encode import parse_line
from banjax_tpu.obs import provenance

log = logging.getLogger(__name__)

OLD_LINE_CUTOFF_SECONDS = 10  # regex_rate_limiter.go:164


class CpuMatcher(Matcher):
    def __init__(
        self,
        config: Config,
        banner: BannerInterface,
        decision_lists: StaticDecisionLists,
        rate_limit_states: RegexRateLimitStates,
    ):
        self.config = config
        self.banner = banner
        self.decision_lists = decision_lists
        self.rate_limit_states = rate_limit_states

    def consume_line(self, line_text: str, now_unix: Optional[float] = None) -> ConsumeLineResult:
        result = ConsumeLineResult()
        config = self.config

        now = time.time() if now_unix is None else now_unix
        p = parse_line(line_text, now, OLD_LINE_CUTOFF_SECONDS)
        if p.error:
            log.warning("could not parse log line: %r", line_text)
            result.error = True
            return result
        if p.old_line:
            result.old_line = True
            return result

        if self.decision_lists.check_is_allowed(p.host, p.ip):
            result.exempted = True
            return result

        # per-site rules for the host first (regex_rate_limiter.go:175-193)
        for rule in config.per_site_regexes_with_rates.get(p.host, []):
            rule_result = self._apply_regex_to_log(
                rule, p.rest, p.timestamp_ns, p.ip, p.host
            )
            if rule_result.regex_match:
                result.rule_results.append(rule_result)

        # then global rules (regex_rate_limiter.go:195-211)
        for rule in config.regexes_with_rates:
            rule_result = self._apply_regex_to_log(
                rule, p.rest, p.timestamp_ns, p.ip, p.host
            )
            if rule_result.regex_match:
                result.rule_results.append(rule_result)

        return result

    def _apply_regex_to_log(
        self,
        rule: RegexWithRate,
        rest: str,
        timestamp_ns: int,
        ip_string: str,
        url_string: str,
    ) -> RuleResult:
        """applyRegexToLog (regex_rate_limiter.go:216-269)."""
        result = RuleResult(rule_name=rule.rule)

        if rule.regex.search(rest) is None:  # Go Regexp.Match = unanchored
            result.regex_match = False
            return result
        result.regex_match = True

        if rule.hosts_to_skip.get(url_string):
            result.skip_host = True
            return result
        result.skip_host = False

        seen_ip, rate_limit_result = self.rate_limit_states.apply(
            ip_string, rule, timestamp_ns
        )
        result.seen_ip = seen_ip
        result.rate_limit_result = rate_limit_result

        if rate_limit_result.exceeded:
            self.banner.ban_or_challenge_ip(
                self.config, ip_string, rule.decision, url_string
            )
            self.banner.log_regex_ban(
                self.config, timestamp_ns / 1e9, ip_string, rule.rule, rest, rule.decision
            )
            provenance.record(
                provenance.SOURCE_RATE_LIMIT, ip_string, rule.decision,
                rule=rule.rule, hits=rule.hits_per_interval + 1,
            )

        return result
