"""Fused matcher + device-windows pipeline, split into two device programs
so chunks can OVERLAP without ever reordering window updates.

Why fused at all: with device windows on, the naive path round-trips the
match bitmap through the host — the matcher pulls its sparse result down
(~65 ms fixed tunnel latency per pull), the runner rebuilds a dense
[B, n_rules] bitmap, and apply_bitmap pushes those ~16 MB back up for the
window scan. Here the dense caller-order bitmap never exists on the host.

Why two programs (PERF.md "path to 5M" 3c): a single fused program forces
strict chunk serialization — if chunk N overflows (its state writes gated
off), its classic re-apply would land on the device stream AFTER an
already-submitted chunk N+1, reordering window updates. Splitting fixes it:

  program A — MATCH (stateless): two-stage match (prefilter._match_core),
    dense caller-order bitmap assembly, and ALL overflow flags — candidate
    count, match-pair count, and the window-event count (it takes
    host_idx + active_table precisely so the event count is known before
    any state is touched). Outputs: one sparse host buffer (flags ‖
    (row, rule) match pairs ‖ always-rule bits) and the device-resident
    bitmap. A dispatches freely, any number of chunks ahead.

  program B — APPLY (window state donated): the window segmented scan
    (windows._apply_core) over A's bitmap. B for chunk i is dispatched
    only after chunk i's A-flags are known ok AND every earlier chunk's
    apply (B or classic fallback) has completed its dispatch — so
    device-stream order equals log order, always. Overflowing chunks never
    dispatch B: the caller drains all earlier chunks, then replays through
    the classic splitting path (state untouched, output identical).

Both pulls (A's sparse buffer, B's event buffer) are async and overlap
later chunks' compute, hiding the tunnel's fixed d2h latency.

Ordering machinery: submit() assigns a sequence number; resolve() and
collect() each gate on it (resolve order = B dispatch order = device apply
order; collect order = host-shadow write order). The shadow must absorb
batches in device-apply order or an eviction could restore stale counters.

Event order parity: bits are scattered into CALLER row order before the
window apply, so the event compaction's row-major (line, rule) order — the
reference's per-site-then-global processing order — is preserved exactly
as in the classic path.

Single-kernel mode (`pallas_single_kernel`, kernels/fused_match_window.py)
collapses A+B into ONE program dispatched at submit: the window commit is
gated IN-KERNEL on the overflow flags and on a device-side chain scalar
(an overflow poisons every already-dispatched successor, which then
replays classically in order), so the host decision between the programs
— and with it the ~65 ms resolve pull — disappears.  submit() returns an
already-final chunk; resolve() is a pure pull of the one combined buffer;
staleness/abandon compose as a per-row live-mask INPUT to submit.  The
two-program protocol below stays intact as the differential oracle and
the fallback when the Pallas window-scan kernel can't lower.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import threading
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from banjax_tpu.matcher import windows as W
from banjax_tpu.obs import trace
from banjax_tpu.matcher.prefilter import FusedPrefilter
from banjax_tpu.matcher.windows import DeviceWindows, WindowEvent
from banjax_tpu.decisions.rate_limit import RateLimitMatchType

log = logging.getLogger(__name__)

_SHIFTS = (0, 8, 16, 24)


@dataclasses.dataclass
class _Pend:
    """One chunk in flight. States: submitted → resolved → done, or
    submitted → overflow → (caller fallback) → done."""

    seq: int
    sparse_buf: object     # program A's buffer (async pull in flight);
    #                        single-kernel mode: THE one combined buffer
    bits_dev: object       # [Bp, n_rules] uint8 device-resident
    slots: np.ndarray      # caller-order, pins held
    ts_s: np.ndarray       # padded to Bp
    ts_ns: np.ndarray      # padded to Bp
    host_idx: np.ndarray   # padded to Bp
    B: int                 # real rows
    Bp: int
    K: int
    P: int
    state: str = "submitted"
    flags: Optional[np.ndarray] = None     # [4] after resolve
    events_buf: object = None              # program B's buffer, or (single-
    #                                        kernel) the decoded host buffer
    events_off: int = 0                    # event-record offset into it
    # decoded at resolve (from the A pull)
    matched_pairs: Optional[np.ndarray] = None
    always_bits: Optional[np.ndarray] = None
    # transfer accounting (obs/stats.py note_xfer): what this chunk moved
    # across the host boundary — the fusion-win witness is the ABSENCE of
    # the dense [B, n_rules] bitmap from h2d_bytes
    h2d_bytes: int = 0
    d2h_bytes: int = 0
    # state-aware settlement: each order turn and the slot pins are
    # released EXACTLY once no matter which combination of resolve/
    # collect/fallback_done/abandon settles the chunk (a submit-failure
    # abandon racing a teardown abort used to mark a turn dead twice,
    # which could advance a counter past a live chunk's turn)
    pins_released: bool = False
    turns_freed: dict = dataclasses.field(
        default_factory=lambda: {"_resolve_seq": False, "_collect_seq": False}
    )


@dataclasses.dataclass
class FusedWindowsResult:
    """Outcome of one collected chunk."""

    events: List[WindowEvent]
    matched_pairs: Optional[np.ndarray]   # int32 caller_row * R8 + bit col
    always_bits: Optional[np.ndarray]     # [B, na8] packed always-rule bits


class PipelineOverflow(RuntimeError):
    """resolve() found an overflow: the caller must finish this chunk via
    the classic fallback (then call fallback_done)."""

    def __init__(self, candidate_overflow: bool):
        super().__init__(
            "candidate capacity exceeded" if candidate_overflow
            else "match-pair/event capacity exceeded"
        )
        # True: stage 2 never saw the excess lines — even the dense bitmap
        # is incomplete and must be recomputed single-stage
        self.candidate_overflow = candidate_overflow


class FusedWindowsPipeline:
    """Built by TpuMatcher when the fused prefilter and device windows are
    both active and every rule is device-decidable.

    Contract: submit in chunk order; resolve and collect each in that same
    order (they gate on it). Pins are owned by the pipeline from submit()
    until collect() completes — except after PipelineOverflow, where the
    caller's fallback apply (which releases them) takes over, followed by
    fallback_done() to release the order turns."""

    def __init__(self, prefilter: FusedPrefilter, windows: DeviceWindows,
                 active_table, n_rules: int, single_kernel: bool = False,
                 scan_interpret: bool = True, traffic_sketch=None):
        self.pf = prefilter
        self.windows = windows
        self.active_table = jnp.asarray(active_table)
        self.n_rules = n_rules
        # traffic introspection (obs/sketch.py): every submitted chunk
        # folds into the device-resident count-min/HLL/rule-pressure
        # sketches as one more stateless array op — telemetry only, no
        # interaction with window state or results
        self._traffic_sketch = traffic_sketch
        self._match_fns = {}
        self._apply_fns = {}
        # single-kernel mode (kernels/fused_match_window.py): submit
        # dispatches ONE program doing match + window commit (state
        # donated, overflow/chain gated in-kernel) and the chunk is final
        # on return; resolve/collect become pure decodes of the one
        # async-pulled buffer.  False = the two-program A/B protocol,
        # which stays intact as the differential oracle and the fallback
        # when the Pallas window-scan kernel can't lower.
        self.single_kernel = bool(single_kernel)
        self._scan_interpret = bool(scan_interpret)
        # device-side ok chain: each kernel's commit gates on its
        # predecessor's ok scalar, so an overflow poisons every already-
        # dispatched successor WITHOUT a host round-trip; None = seed the
        # next submit with a fresh ok (no poisoned chunk outstanding)
        self._chain_ok = None
        self.sk_chunks = 0          # single-kernel chunks committed
        self.sk_fallbacks = 0       # routed to the classic fallback
        self.sk_d2h_bytes_total = 0  # the one-pull d2h witness
        plan = prefilter.plan
        self._f_idx = jnp.asarray(plan.f_idx, dtype=jnp.int32)
        self._a_idx = jnp.asarray(plan.a_idx, dtype=jnp.int32)
        na = plan.n_always
        self._aw = jnp.asarray(
            np.asarray(plan.stage1.always_match[:na], dtype=np.uint8)
        )
        self._ae = jnp.asarray(
            np.asarray(plan.stage1.empty_only[:na], dtype=np.uint8)
        )
        self.fused_batches = 0
        self.fallback_batches = 0
        self._cv = threading.Condition()
        self._next_seq = 0      # assigned at submit
        self._resolve_seq = 0   # B-dispatch order
        self._collect_seq = 0   # shadow-write order
        # turns of chunks that died before taking them (resolve failure,
        # abandon): swept lazily when the counter reaches them — advancing
        # out of turn would steal an earlier live chunk's turn
        self._dead = {"_resolve_seq": set(), "_collect_seq": set()}

    # ---- program A: stateless match + flags ----

    def _match_prog(self, Bp: int, L_p: int):
        key = (Bp, L_p)
        hit = self._match_fns.get(key)
        if hit is not None:
            return hit
        pf = self.pf
        plan = pf.plan
        block, K = pf.capacities(Bp)
        core = pf._match_core(Bp, L_p, K, block)
        P = pf.pair_capacity(Bp, K)
        n_rules, n_filt = self.n_rules, plan.stage2.n_rules
        n_always = plan.n_always
        f_idx, a_idx = self._f_idx, self._a_idx
        aw, ae = self._aw, self._ae
        max_events = self.windows.max_events
        active_table = self.active_table
        shifts = jnp.asarray(_SHIFTS, dtype=jnp.int32)

        @jax.jit
        def match(combined, n_real, host_idx):
            c = core(combined)
            # sparse (row, rule) pair output — the shared encoding
            # (prefilter.pairs_from_core): one int32 per set stage-2 bit
            # instead of a packed row bitmap per matched line (~30x less
            # d2h on the tunnel, whose ~20-25 MB/s would otherwise
            # dominate the chunk budget). pair_bits doubles as the dense
            # per-candidate form for the bitmap assembly below.
            pairs, n_pairs, pair_bits = pf.pairs_from_core(c, K, P)
            # dense caller-order bitmap, assembled on device
            m2 = pair_bits[:, :n_filt].astype(jnp.uint8)         # [K, n_filt]
            filt = jnp.zeros((Bp + 1, n_filt), dtype=jnp.uint8)
            filt = filt.at[c["idx_caller_k"]].set(m2)[:Bp]       # row Bp = dump
            bits = jnp.zeros((Bp, n_rules), dtype=jnp.uint8)
            bits = bits.at[:, f_idx].set(filt)
            ab = None
            if n_always:
                ab = c["ab_caller"] | aw[None, :]
                empty = (c["lens_raw"] == 0).astype(jnp.uint8)[:, None]
                ab = ab | (ae[None, :] * empty)
                bits = bits.at[:, a_idx].set(ab)
            # padding rows (row >= n_real) can still carry bits — e.g. an
            # always_match rule's column is all-ones — and MUST NOT reach
            # the window apply: their pad slot id belongs to a real IP
            real = jax.lax.iota(jnp.int32, Bp) < n_real
            bits = bits * real[:, None].astype(jnp.uint8)
            # the window-event count, computed HERE so every overflow
            # condition is known before any state is touched
            fire = (bits != 0) & active_table[host_idx]
            n_events = fire.sum(dtype=jnp.int32)
            ok = (
                (c["n_cand"] <= K) & (n_pairs <= P)
                & (n_events <= max_events)
            )
            flags = jnp.stack([
                ok.astype(jnp.int32), c["n_cand"], n_pairs, n_events,
            ])
            parts = [
                ((flags[:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                ((pairs[:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
            ]
            if n_always:
                # sparse rows cover only the filterable rules; replay
                # bookkeeping needs the completed always-rule bits too
                parts.append(
                    jnp.packbits(ab.astype(jnp.bool_), axis=1).reshape(-1)
                )
            return jnp.concatenate(parts), bits

        self._match_fns[key] = (match, K, P)
        return match, K, P

    # ---- single-kernel program: match + window commit in ONE dispatch ----

    def _single_prog(self, Bp: int, L_p: int):
        """The fused match+window program (single-kernel mode), cached in
        the same per-(Bp, L_p) table as the two-program match — the modes
        are exclusive per pipeline, so the cache never mixes kinds."""
        key = (Bp, L_p)
        hit = self._match_fns.get(key)
        if hit is not None:
            return hit
        from banjax_tpu.matcher.kernels import fused_match_window as fmw

        fn, K, P = fmw.build_single_program(
            self.pf, self.windows, self.active_table, self.n_rules,
            Bp, L_p, f_idx=self._f_idx, a_idx=self._a_idx,
            aw=self._aw, ae=self._ae,
            scan_fn=fmw.window_scan(self._scan_interpret),
        )
        self._match_fns[key] = (fn, K, P)
        return fn, K, P

    def _submit_single(self, combined, Bp: int, L_p: int, B: int,
                       slots_p, ts_s_p, ts_ns_p, host_idx_p,
                       live: Optional[np.ndarray]) -> _Pend:
        """Dispatch the single fused program for one chunk: the window
        state commit happens HERE (gated in-kernel on overflow and on the
        chain scalar), so the returned chunk is already final — its
        resolve is a pure pull.  Runs under the windows lock: maintenance
        (evictions/restores) drains first, exactly as the two-program
        resolve did, and the state-chain order == seq order because both
        are taken inside the same critical section."""
        fn, K, P = self._single_prog(Bp, L_p)
        live_p = np.zeros(Bp, dtype=np.uint8)
        live_p[:B] = 1 if live is None else np.asarray(live, dtype=np.uint8)
        wnd = self.windows
        with wnd._lock:
            with self._cv:
                seq = self._next_seq
                # quiescent chain reseed: every submitted chunk resolved
                # ⟹ every poisoned chunk's classic fallback has applied,
                # so a fresh ok seed cannot reorder window updates
                if seq == self._resolve_seq:
                    self._chain_ok = None
                self._next_seq += 1
                chain = self._chain_ok
            wnd._run_maintenance_locked()
            new_state, chain_out, buf, bits_dev = fn(
                wnd._state,
                chain if chain is not None else jnp.int32(1),
                jnp.asarray(combined), jnp.int32(B),
                jnp.asarray(host_idx_p), jnp.asarray(slots_p),
                jnp.asarray(ts_s_p), jnp.asarray(ts_ns_p),
                jnp.asarray(live_p),
            )
            wnd._state = new_state
            with self._cv:
                self._chain_ok = chain_out
        try:
            buf.copy_to_host_async()
        except AttributeError:
            pass
        return _Pend(
            seq=seq, sparse_buf=buf, bits_dev=bits_dev,
            slots=slots_p,  # caller overwrites with the unpadded view
            ts_s=ts_s_p, ts_ns=ts_ns_p, host_idx=host_idx_p,
            B=B, Bp=Bp, K=K, P=P,
            # the whole h2d for the chunk: encoded classes + per-row
            # window metadata + the live mask + the chain scalar — still
            # no dense [B, n_rules] bitmap
            h2d_bytes=combined.nbytes + 4 * 3 * Bp + Bp + 4,
        )

    # ---- program B: window apply on a device-resident bitmap ----

    def _apply_prog(self, Bp: int):
        hit = self._apply_fns.get(Bp)
        if hit is not None:
            return hit
        wnd = self.windows
        n_rules = self.n_rules
        max_events = wnd.max_events
        limits, iv_s, iv_ns = wnd._limits, wnd._iv_s, wnd._iv_ns
        active_table = self.active_table
        shifts = jnp.asarray(_SHIFTS, dtype=jnp.int32)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def apply(state, bits, slots, ts_s, ts_ns, host_idx, live):
            # `live` gates rows that aged past the staleness cutoff while
            # queued in the streaming pipeline: the deferred commit drops
            # them HERE (a handful of bytes h2d) instead of re-uploading a
            # row-filtered dense bitmap
            bits = bits * live[:, None]
            new_state, ev = W._apply_core(
                state, bits, active_table, host_idx, slots, ts_s, ts_ns,
                limits, iv_s, iv_ns,
                n_rules=n_rules, max_events=max_events,
            )
            parts = [
                ((ev["line"][:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                ((ev["rule"][:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                ((ev["hits"][:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                ((ev["start_s"][:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                ((ev["start_ns"][:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                ev["match_type"].astype(jnp.uint8),
                ev["exceeded"].astype(jnp.uint8),
                ev["seen_ip"].astype(jnp.uint8),
            ]
            return new_state, jnp.concatenate(parts)

        self._apply_fns[Bp] = apply
        return apply

    # ---- host API (submit → resolve → collect, each in chunk order) ----

    def submit(
        self, cls_ids: np.ndarray, lens: np.ndarray, slots: np.ndarray,
        ts_s: np.ndarray, ts_ns: np.ndarray, host_idx: np.ndarray,
        live: Optional[np.ndarray] = None,
    ) -> _Pend:
        """Dispatch program A for one chunk (slot pins held by the caller,
        ownership passes to the pipeline). Any number of chunks may be
        submitted ahead of their resolves.

        Single-kernel mode: the ONE fused program (match + window commit,
        overflow/chain gated in-kernel) dispatches here instead and the
        chunk returns already final; `live` (bool [B], default all-true)
        is the commit mask — the caller's staleness/abandon drop composed
        as a kernel input (the two-program path takes it at resolve)."""
        pf = self.pf
        cls_ids = np.asarray(cls_ids, dtype=np.int32)
        lens = np.asarray(lens, dtype=np.int32)
        B = cls_ids.shape[0]
        combined, Bp, L_p = pf._assemble(cls_ids, lens)

        def pad(a, fill=0):
            a = np.asarray(a)
            if Bp == len(a):
                return a
            return np.concatenate(
                [a, np.full(Bp - len(a), fill, dtype=a.dtype)]
            )

        host_idx_p = pad(host_idx).astype(np.int32)
        if self.single_kernel:
            p = self._submit_single(
                combined, Bp, L_p, B,
                pad(np.asarray(slots, dtype=np.int32)),
                pad(ts_s).astype(np.int32), pad(ts_ns).astype(np.int32),
                host_idx_p, live,
            )
            p.slots = np.asarray(slots)
            self._sketch_update(p)
            return p
        match, K, P = self._match_prog(Bp, L_p)
        sparse_buf, bits_dev = match(
            jnp.asarray(combined), jnp.int32(B), jnp.asarray(host_idx_p)
        )
        try:
            sparse_buf.copy_to_host_async()
        except AttributeError:
            pass
        with self._cv:
            seq = self._next_seq
            self._next_seq += 1
        p = _Pend(
            seq=seq, sparse_buf=sparse_buf, bits_dev=bits_dev,
            slots=np.asarray(slots),
            ts_s=pad(ts_s).astype(np.int32),
            ts_ns=pad(ts_ns).astype(np.int32),
            host_idx=host_idx_p, B=B, Bp=Bp, K=K, P=P,
            # the whole host→device traffic for this chunk: the encoded
            # class array + the per-row window metadata — crucially NOT a
            # dense [B, n_rules] bitmap
            h2d_bytes=combined.nbytes + 4 * 3 * Bp,
        )
        self._sketch_update(p)
        return p

    def _sketch_update(self, p: _Pend) -> None:
        """Fold one submitted chunk's rows into the count-min/HLL
        sketches (keyed on the slot ids already bound for the device).
        Unconditional at submit — an overflowed chunk's classic replay
        does NOT re-fold, so each line counts exactly once on this
        path."""
        if self._traffic_sketch is None:
            return
        try:
            self._traffic_sketch.update(p.slots, p.B)
        except Exception:  # noqa: BLE001 — telemetry must never cost a chunk
            log.exception("traffic sketch update failed")

    def _wait_turn(self, p: _Pend, attr: str) -> None:
        with self._cv:
            if getattr(self, attr) == p.seq:
                return
        # the drain thread blocking on an out-of-order turn is exactly
        # the stall a trace must show; the fast path above stays lock+
        # check only (the span records nothing when tracing is off)
        with trace.span("turn-wait", args={"seq": p.seq, "gate": attr}):
            with self._cv:
                while getattr(self, attr) != p.seq:
                    self._cv.wait()

    def _sweep_locked(self, attr: str, v: int) -> None:
        dead = self._dead[attr]
        while v in dead:
            dead.discard(v)
            v += 1
        setattr(self, attr, v)
        self._cv.notify_all()

    def _free_turn(self, p: _Pend, attr: str) -> None:
        """Release one of p's order turns EXACTLY once (state-aware: a
        chunk settled by two paths — e.g. a submit-failure abandon racing
        a teardown abort — must not mark its turn dead twice, which
        would leave a stale entry that could swallow a LATER chunk's
        legitimate turn when seq numbers wrap past it)."""
        with self._cv:
            if p.turns_freed[attr]:
                return
            p.turns_freed[attr] = True
            cur = getattr(self, attr)
            if cur == p.seq:
                self._sweep_locked(attr, p.seq + 1)
            else:
                self._dead[attr].add(p.seq)
                self._sweep_locked(attr, cur)

    def _release_chunk_pins(self, p: _Pend) -> None:
        """Release p's slot pins exactly once.  Double release is the
        REAL hazard the per-chunk flag closes: pins count per slot, so a
        second decrement would release a pin held by a DIFFERENT in-
        flight chunk on the same slot and let the LRU evict state whose
        events are still queued."""
        if p.pins_released:
            return
        p.pins_released = True
        self.windows.release_pins(p.slots)

    def abandon(self, p: _Pend) -> None:
        """Settle a chunk whose apply will never run (pipeline teardown,
        a failed submit burst, or a fully-stale chunk at drain): release
        its pins and both order turns, each exactly once (idempotent —
        see _free_turn/_release_chunk_pins).  Two-program mode: program A
        is stateless, so an abandoned chunk leaves no trace.  Single-
        kernel mode: the commit already happened at submit, so abandon
        only settles the host-side bookkeeping (teardown paths mark the
        chunk's lines as errors)."""
        if p.state in ("done", "failed", "resolved"):
            return
        p.state = "failed"
        self._release_chunk_pins(p)
        self._free_turn(p, "_resolve_seq")
        self._free_turn(p, "_collect_seq")

    def idle(self) -> bool:
        """True when no submitted chunk is awaiting its apply/collect."""
        with self._cv:
            return self._next_seq == self._collect_seq

    def _decode_head(self, p: _Pend, buf: np.ndarray) -> int:
        """Decode the match head (flags ‖ pairs ‖ always bits) shared
        byte-for-byte by program A's buffer and the single-kernel buffer;
        returns the offset just past it (the single-kernel event tail)."""
        P = p.P
        R8 = self.pf._nf8 * 8
        flags = np.frombuffer(buf[:16].tobytes(), dtype="<i4")
        p.flags = flags
        off = 16
        pairs = np.frombuffer(
            buf[off : off + 4 * P].tobytes(), dtype="<i4"
        )
        off += 4 * P
        na8 = self.pf._na8
        if na8:
            p.always_bits = (
                buf[off : off + p.Bp * na8].reshape(-1, na8)[: p.B]
            )
            off += p.Bp * na8
        else:
            p.always_bits = None
        n_pairs = int(flags[2])
        if n_pairs <= P:
            live_pairs = pairs[:n_pairs]
            rows_idx = live_pairs // R8
            cols = live_pairs - rows_idx * R8
            # same invariant as prefilter.collect: row in range AND
            # col within the true rule count, so matched_pairs is a
            # clean invariant at the source (consumers may index f_idx
            # with it directly)
            keep = (
                (rows_idx >= 0) & (rows_idx < p.B)
                & (cols < self.pf.plan.stage2.n_rules)
            )
            p.matched_pairs = live_pairs[keep]
        return off

    def _resolve_single(self, p: _Pend) -> None:
        """Single-kernel resolve: a PURE d2h pull — the commit already
        happened in-kernel at submit, so all that remains is forcing the
        (async-copied) buffer and reading the flags word.  Not-ok chunks
        (own overflow, or gated by a poisoned predecessor) take the
        classic fallback exactly like a two-program overflow; the resolve
        turn is held until fallback_done, so later chunks' replays stay
        behind this chunk's classic apply."""
        try:
            buf = np.asarray(p.sparse_buf)
            p.d2h_bytes += buf.nbytes
            off = self._decode_head(p, buf)
            flags = p.flags
            if not flags[0]:
                p.state = "overflow"
                self.fallback_batches += 1
                self.sk_fallbacks += 1
                raise PipelineOverflow(
                    candidate_overflow=int(flags[1]) > p.K
                )
            p.events_buf = buf
            p.events_off = off
            p.state = "resolved"
            self.fused_batches += 1
            self.sk_chunks += 1
            self.sk_d2h_bytes_total += buf.nbytes
        except PipelineOverflow:
            raise  # turns advance via fallback_done after the fallback
        except Exception:
            p.state = "failed"
            self._release_chunk_pins(p)
            self._free_turn(p, "_resolve_seq")
            self._free_turn(p, "_collect_seq")
            raise
        self._free_turn(p, "_resolve_seq")

    def resolve(self, p: _Pend, live: Optional[np.ndarray] = None) -> None:
        """Order-gated: decode chunk p's A-flags; when ok, dispatch program
        B (the window apply) — B dispatches therefore happen strictly in
        chunk order. `live` (bool [B], default all-true) gates rows out of
        the window commit — the streaming pipeline's drain-time staleness
        drop composed with the deferred apply. Raises PipelineOverflow when
        the chunk must take the classic fallback; the resolve turn is NOT
        advanced until the caller completes the fallback (fallback_done),
        keeping later chunks' applies behind this chunk's.

        Single-kernel mode: the commit already ran at submit (live was an
        input there); this is a pure pull + flags check — `live` must be
        None."""
        self._wait_turn(p, "_resolve_seq")
        if p.state != "submitted":
            return
        if self.single_kernel:
            assert live is None, "single-kernel commit takes live at submit"
            return self._resolve_single(p)
        try:
            buf = np.asarray(p.sparse_buf)
            p.d2h_bytes += buf.nbytes
            self._decode_head(p, buf)
            flags = p.flags
            if not flags[0]:
                p.state = "overflow"
                self.fallback_batches += 1
                raise PipelineOverflow(
                    candidate_overflow=int(flags[1]) > p.K
                )

            wnd = self.windows
            apply = self._apply_prog(p.Bp)
            slots_p = p.slots.astype(np.int32)
            if p.Bp != p.B:
                slots_p = np.concatenate(
                    [slots_p, np.zeros(p.Bp - p.B, dtype=np.int32)]
                )
            live_p = np.ones(p.Bp, dtype=np.uint8)
            if live is not None:
                live_p[: p.B] = np.asarray(live, dtype=np.uint8)
            p.h2d_bytes += live_p.nbytes
            with wnd._lock:
                wnd._run_maintenance_locked()
                new_state, ebuf = apply(
                    wnd._state, p.bits_dev, jnp.asarray(slots_p),
                    jnp.asarray(p.ts_s), jnp.asarray(p.ts_ns),
                    jnp.asarray(p.host_idx), jnp.asarray(live_p),
                )
                wnd._state = new_state
            try:
                ebuf.copy_to_host_async()
            except AttributeError:
                pass
            p.events_buf = ebuf
            p.state = "resolved"
            self.fused_batches += 1
        except PipelineOverflow:
            raise  # turns advance via fallback_done after the fallback
        except Exception:
            # the chunk is dead: free its order turns (a stuck turn would
            # deadlock every later resolve/collect forever) and the pins.
            # The resolve turn is held by this call (current == p.seq) so
            # _free_turn advances it directly; the collect turn may still
            # belong to an EARLIER uncollected chunk and sweeps lazily.
            p.state = "failed"
            self._release_chunk_pins(p)
            self._free_turn(p, "_resolve_seq")
            self._free_turn(p, "_collect_seq")
            raise
        self._free_turn(p, "_resolve_seq")

    def fallback_done(self, p: _Pend) -> None:
        """The caller's classic fallback for an overflowing chunk is fully
        applied (device + shadow + pins released by apply_bitmap): release
        both order turns.  The pins are marked settled so a later abandon
        (teardown racing the fallback) cannot release them a second time."""
        p.state = "done"
        p.pins_released = True  # apply_bitmap released them
        self._free_turn(p, "_resolve_seq")
        self._free_turn(p, "_collect_seq")
        if self.single_kernel:
            # quiescent chain reseed (see _submit_single): if no later
            # chunk is outstanding, every poisoned chunk has now applied
            # classically, so the next submit may start a fresh ok chain
            with self._cv:
                if self._next_seq == self._resolve_seq:
                    self._chain_ok = None

    def collect(self, p: _Pend) -> FusedWindowsResult:
        """Order-gated on the collect turn: decode chunk p's window events,
        absorb the final counter states into the host shadow, release the
        pins. Only valid for resolved chunks (collect() resolves first on
        the serial convenience path).  Single-kernel mode decodes the
        event tail of the ONE buffer resolve already pulled (no second
        d2h — the event layout is byte-identical to program B's)."""
        if p.state == "submitted":
            self.resolve(p)  # may raise PipelineOverflow to the caller
        assert p.state == "resolved", p.state
        self._wait_turn(p, "_collect_seq")
        wnd = self.windows
        try:
            if self.single_kernel:
                buf = p.events_buf  # already host-side, pulled at resolve
                off = p.events_off
            else:
                buf = np.asarray(p.events_buf)
                p.d2h_bytes += buf.nbytes
                off = 0
            me = wnd.max_events

            def take_i32(n):
                nonlocal off
                out = np.frombuffer(
                    buf[off : off + 4 * n].tobytes(), dtype="<i4"
                )
                off += 4 * n
                return out

            ev_line = take_i32(me)
            ev_rule = take_i32(me)
            ev_hits = take_i32(me)
            ev_ss = take_i32(me)
            ev_sns = take_i32(me)
            ev_mtype = buf[off : off + me]; off += me
            ev_exc = buf[off : off + me]; off += me
            ev_seen = buf[off : off + me]; off += me

            live = np.flatnonzero(ev_rule >= 0)
            events = [
                WindowEvent(
                    line=int(ev_line[k]),
                    rule_id=int(ev_rule[k]),
                    match_type=RateLimitMatchType(int(ev_mtype[k])),
                    exceeded=bool(ev_exc[k]),
                    seen_ip=bool(ev_seen[k]),
                )
                for k in live
            ]
            # shadow update mirrors _apply_bitmap_inner: (line, rule) order
            # so dict INSERTION order matches the reference's
            # first-matched-event order (format_states parity); last write
            # per (ip, rule) is still the chronologically-final state.
            # Collect order == apply order, so concurrent chunks can't
            # interleave stale values.
            from collections import OrderedDict

            shorder = np.lexsort((ev_rule[live], ev_line[live]))
            with wnd._lock:
                for k in live[shorder]:
                    ip = wnd._slot_ip.get(int(p.slots[int(ev_line[k])]))
                    if ip is None:
                        continue
                    od = wnd._shadow.setdefault(ip, OrderedDict())
                    od[int(ev_rule[k])] = (
                        int(ev_hits[k]), int(ev_ss[k]), int(ev_sns[k])
                    )
            events.sort(key=lambda e: (e.line, e.rule_id))
            p.state = "done"
            return FusedWindowsResult(
                events=events, matched_pairs=p.matched_pairs,
                always_bits=p.always_bits,
            )
        finally:
            self._release_chunk_pins(p)
            self._free_turn(p, "_collect_seq")
