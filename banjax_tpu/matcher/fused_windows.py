"""Fused matcher + device-windows pipeline: one device dispatch per batch.

Without this, the device-windows path round-trips the match bitmap through
the host: the fused matcher pulls its sparse result down (~65 ms fixed
tunnel latency), the runner reconstructs a dense [B, n_rules] bitmap, and
apply_bitmap pushes those ~16 MB straight back up for the window scan —
two transfers and an extra dispatch of pure overhead on the hot path
(BASELINE.json configs[4]/[5], the live-stream shape).

Here the dense caller-order bitmap never exists on the host: the two-stage
match (prefilter._match_core) and the window apply (windows._apply_core)
trace into ONE jit. Per batch the host sends the combined class array plus
four small per-line vectors (slots, ts_s, ts_ns, host row), and receives
ONE buffer: overflow flags ‖ window events ‖ the sparse matched rows for
ConsumeLineResult bookkeeping. The window state is donated through the
dispatch; all three overflow conditions (candidates > K, matched rows > E,
events > max_events) gate every state write OFF on device (windows
_apply_core `gate`), so an overflowing batch leaves the counters
bit-identical and the caller reruns it through the classic splitting path
using the dense bitmap — which this program also returns as a
device-resident output (free unless that fallback actually pulls it).

Event order parity: bits are scattered into CALLER row order before the
window apply, so the event compaction's row-major (line, rule) order — the
reference's per-site-then-global processing order — is preserved exactly
as in the classic path.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from banjax_tpu.matcher import windows as W
from banjax_tpu.matcher.prefilter import FusedPrefilter
from banjax_tpu.matcher.windows import DeviceWindows, WindowEvent
from banjax_tpu.decisions.rate_limit import RateLimitMatchType

log = logging.getLogger(__name__)


@dataclasses.dataclass
class _PendingBatch:
    buf: object            # uint8 result buffer (copy_to_host_async started)
    bits_dev: object       # [B, n_rules] uint8 device-resident (fallback use)
    slots: np.ndarray      # caller-order slot per line (pins held)
    ts_s: np.ndarray
    ts_ns: np.ndarray
    host_idx: np.ndarray
    B: int                 # real rows
    K: int
    E: int
    seq: int = 0           # submit order (collects must match it)


class FusedWindowsPipeline:
    """Builds and runs the single-dispatch match+windows program.

    Constructed by TpuMatcher when both the fused prefilter and device
    windows are active. submit() must be called with the windows slot pins
    already held (slots_for_ips); collect() consumes the events, updates
    the host shadow, and releases the pins — or runs the classic fallback
    on overflow (which releases them itself)."""

    def __init__(self, prefilter: FusedPrefilter, windows: DeviceWindows,
                 active_table, n_rules: int):
        self.pf = prefilter
        self.windows = windows
        self.active_table = jnp.asarray(active_table)
        self.n_rules = n_rules
        self._fns = {}
        plan = prefilter.plan
        self._f_idx = jnp.asarray(plan.f_idx, dtype=jnp.int32)
        self._a_idx = jnp.asarray(plan.a_idx, dtype=jnp.int32)
        na = plan.n_always
        self._aw = jnp.asarray(
            np.asarray(plan.stage1.always_match[:na], dtype=np.uint8)
        )
        self._ae = jnp.asarray(
            np.asarray(plan.stage1.empty_only[:na], dtype=np.uint8)
        )
        # overflows observable in metrics
        self.fused_batches = 0
        self.fallback_batches = 0
        # collect-order gate: the host shadow must absorb batches in the
        # order their device applies ran (= submit order). Concurrent
        # callers' collects serialize on this sequence — the same
        # invariant windows._apply_bitmap_inner keeps by doing the state
        # swap and the shadow write in one lock window.
        import threading

        self._seq_cv = threading.Condition()
        self._next_seq = 0
        self._collect_seq = 0

    # ---- device program ----

    def _step(self, B: int, L_p: int):
        key = (B, L_p)
        hit = self._fns.get(key)
        if hit is not None:
            return hit
        pf, wnd = self.pf, self.windows
        plan = pf.plan
        block, K, E = pf.capacities(B)
        core = pf._match_core(B, L_p, K, E, block)
        n_rules, n_filt = self.n_rules, plan.stage2.n_rules
        n_always = plan.n_always
        f_idx, a_idx = self._f_idx, self._a_idx
        aw, ae = self._aw, self._ae
        max_events = wnd.max_events
        limits, iv_s, iv_ns = wnd._limits, wnd._iv_s, wnd._iv_ns
        active_table = self.active_table
        shifts = jnp.asarray([0, 8, 16, 24], dtype=jnp.int32)

        def unpack_rule_bits(packed):  # [K, nf8] -> [K, n_filt] uint8 0/1
            b = (packed[:, :, None] >> (7 - jnp.arange(8, dtype=jnp.uint8))) & 1
            return b.reshape(packed.shape[0], -1)[:, :n_filt]

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state, combined, n_real, slots, ts_s, ts_ns, host_idx):
            c = core(combined)
            # dense caller-order bitmap, assembled on device
            m2 = unpack_rule_bits(c["m2p"])                      # [K, n_filt]
            filt = jnp.zeros((B + 1, n_filt), dtype=jnp.uint8)
            filt = filt.at[c["idx_caller_k"]].set(m2)[:B]        # row B = dump
            bits = jnp.zeros((B, n_rules), dtype=jnp.uint8)
            bits = bits.at[:, f_idx].set(filt)
            if n_always:
                ab = c["ab_caller"] | aw[None, :]
                empty = (c["lens_raw"] == 0).astype(jnp.uint8)[:, None]
                ab = ab | (ae[None, :] * empty)
                bits = bits.at[:, a_idx].set(ab)

            # padding rows (row >= n_real) can still carry bits — e.g. an
            # always_match rule's column is all-ones — and MUST NOT reach
            # the window apply: their pad slot id 0 belongs to a real IP.
            # Mask the bitmap itself; _apply_core derives its fires from it.
            real = jax.lax.iota(jnp.int32, B) < n_real
            bits = bits * real[:, None].astype(jnp.uint8)
            fire = (bits != 0) & active_table[host_idx]
            n_events = fire.sum(dtype=jnp.int32)
            ok = (
                (c["n_cand"] <= K) & (c["n_m"] <= E)
                & (n_events <= max_events)
            )
            new_state, ev = W._apply_core(
                state, bits, active_table, host_idx, slots, ts_s, ts_ns,
                limits, iv_s, iv_ns,
                n_rules=n_rules, max_events=max_events, gate=ok,
            )
            flags = jnp.stack([
                ok.astype(jnp.int32), c["n_cand"], c["n_m"], n_events,
            ])
            parts = [
                ((flags[:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                # window events (reference order after host sort by
                # (line, rule)): int32 lanes for line/rule/hits/ss/sns,
                # uint8 for mtype/exceeded/seen
                ((ev["line"][:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                ((ev["rule"][:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                ((ev["hits"][:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                ((ev["start_s"][:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                ((ev["start_ns"][:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                ev["match_type"].astype(jnp.uint8),
                ev["exceeded"].astype(jnp.uint8),
                ev["seen_ip"].astype(jnp.uint8),
                # sparse matched rows for ConsumeLineResult bookkeeping
                ((c["idx_caller"][:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                c["rows"].reshape(-1),
            ]
            if n_always:
                # always-rule bits per line: the sparse rows cover only the
                # filterable rules, but replay bookkeeping needs e.g. a
                # catch-all `.*` rule's per-line matches too. Pack the
                # COMPLETED ab (static always_match/empty_only flags
                # included), not the raw branch accepts.
                parts.append(
                    jnp.packbits(ab.astype(jnp.bool_), axis=1).reshape(-1)
                )
            return new_state, jnp.concatenate(parts), bits

        self._fns[key] = (step, K, E)
        return step, K, E

    # ---- host API ----

    def submit(
        self, cls_ids: np.ndarray, lens: np.ndarray, slots: np.ndarray,
        ts_s: np.ndarray, ts_ns: np.ndarray, host_idx: np.ndarray,
    ) -> _PendingBatch:
        """Dispatch one batch (slot pins held by the caller). The window
        state swap happens here under the windows lock — device-stream
        order then guarantees a later batch's maintenance (evictions /
        restores) executes after this batch's apply."""
        pf, wnd = self.pf, self.windows
        cls_ids = np.asarray(cls_ids, dtype=np.int32)
        lens = np.asarray(lens, dtype=np.int32)
        B = cls_ids.shape[0]
        combined, Bp, L_p = pf._assemble(cls_ids, lens)
        step, K, E = self._step(Bp, L_p)

        def pad(a, fill=0):
            a = np.asarray(a)
            if Bp == B:
                return a
            return np.concatenate(
                [a, np.full(Bp - B, fill, dtype=a.dtype)]
            )

        with wnd._lock:
            wnd._run_maintenance_locked()
            new_state, buf, bits_dev = step(
                wnd._state, jnp.asarray(combined), jnp.int32(B),
                jnp.asarray(pad(slots)), jnp.asarray(pad(ts_s)),
                jnp.asarray(pad(ts_ns)), jnp.asarray(pad(host_idx)),
            )
            wnd._state = new_state
        try:
            buf.copy_to_host_async()
        except AttributeError:
            pass
        with self._seq_cv:
            seq = self._next_seq
            self._next_seq += 1
        return _PendingBatch(
            buf=buf, bits_dev=bits_dev, slots=np.asarray(slots),
            ts_s=np.asarray(ts_s), ts_ns=np.asarray(ts_ns),
            host_idx=np.asarray(host_idx), B=B, K=K, E=E, seq=seq,
        )

    def collect(self, p: _PendingBatch) -> "FusedWindowsResult":
        """Block on a submit()ed batch (collects serialize in submit order
        so shadow writes land in device-apply order). Overflow taxonomy:

        * fused ok — events + sparse matched rows decode from the buffer,
          the host shadow updates, pins release here.
        * candidates fit K but rows/events overflowed — the dense device
          bitmap IS complete; the batch replays through the classic
          apply_bitmap (splits as needed, releases the pins itself). The
          sparse rows are valid only when n_m <= E; otherwise the caller
          reads result.bits (one dense pull, rare path).
        * candidates overflowed K — stage 2 never saw the excess lines, so
          even the dense bitmap is incomplete: events is None, bits is
          None, and the PINS STAY HELD — the caller must recompute the
          bitmap single-stage and run apply_bitmap with the same slots
          (which releases them).
        """
        # serialize collects in submit order: a later batch's shadow write
        # landing before an earlier one would leave stale counters that an
        # eviction could later restore as authoritative
        with self._seq_cv:
            while self._collect_seq != p.seq:
                self._seq_cv.wait()
        # pin ownership: exactly one release on every path. _collect_inner
        # moves ownership forward ('released' after its own release,
        # 'applied' once apply_bitmap — which releases internally — is
        # entered, 'caller' when returning pins_held=True); an exception
        # while still 'collect' releases here.
        owner = ["collect"]
        try:
            return self._collect_inner(p, owner)
        except Exception:
            if owner[0] == "collect":
                self.windows.release_pins(p.slots)
            raise
        finally:
            with self._seq_cv:
                self._collect_seq += 1
                self._seq_cv.notify_all()

    def _collect_inner(self, p: _PendingBatch, owner) -> "FusedWindowsResult":
        wnd = self.windows
        max_events = wnd.max_events
        E = p.E
        buf = np.asarray(p.buf)
        off = 0

        def take_i32(n):
            nonlocal off
            out = np.frombuffer(buf[off : off + 4 * n].tobytes(), dtype="<i4")
            off += 4 * n
            return out

        def take_u8(n):
            nonlocal off
            out = buf[off : off + n]
            off += n
            return out

        flags = take_i32(4)
        ok = bool(flags[0])
        n_cand, n_m = int(flags[1]), int(flags[2])
        ev_line = take_i32(max_events)
        ev_rule = take_i32(max_events)
        ev_hits = take_i32(max_events)
        ev_ss = take_i32(max_events)
        ev_sns = take_i32(max_events)
        ev_mtype = take_u8(max_events)
        ev_exc = take_u8(max_events)
        ev_seen = take_u8(max_events)
        midx = take_i32(E)
        nf8 = self.pf._nf8
        rows = take_u8(E * nf8).reshape(E, nf8)
        na8 = self.pf._na8
        always_bits = (
            buf[off:].reshape(-1, na8)[: p.B] if na8 else None
        )

        def sparse():
            if n_m > E:
                return None, None
            live = midx[:n_m]
            keep = (live >= 0) & (live < p.B)
            return live[keep], rows[:n_m][keep]

        if ok:
            self.fused_batches += 1
            live = np.flatnonzero(ev_rule >= 0)
            events = [
                WindowEvent(
                    line=int(ev_line[k]),
                    rule_id=int(ev_rule[k]),
                    match_type=RateLimitMatchType(int(ev_mtype[k])),
                    exceeded=bool(ev_exc[k]),
                    seen_ip=bool(ev_seen[k]),
                )
                for k in live
            ]
            # shadow update mirrors _apply_bitmap_inner: key-sorted
            # event order, last write per (ip, rule) wins
            from collections import OrderedDict

            with wnd._lock:
                for k in live:
                    ip = wnd._slot_ip.get(int(p.slots[int(ev_line[k])]))
                    if ip is None:
                        continue
                    od = wnd._shadow.setdefault(ip, OrderedDict())
                    od[int(ev_rule[k])] = (
                        int(ev_hits[k]), int(ev_ss[k]), int(ev_sns[k])
                    )
            events.sort(key=lambda e: (e.line, e.rule_id))
            m_rows, m_bits = sparse()
            owner[0] = "released"
            wnd.release_pins(p.slots)
            return FusedWindowsResult(
                events=events, matched_rows=m_rows,
                matched_bits=m_bits, always_bits=always_bits,
                bits_dev=p.bits_dev, pins_held=False,
            )

        self.fallback_batches += 1
        if n_cand > p.K:
            # incomplete bitmap: caller recomputes single-stage and runs
            # apply_bitmap with p.slots (pins stay held until then)
            owner[0] = "caller"
            return FusedWindowsResult(
                events=None, matched_rows=None, matched_bits=None,
                always_bits=None, bits_dev=None, pins_held=True,
            )
        # bitmap complete: classic replay (splits, updates shadow,
        # releases pins); slice off the padding rows so the row count
        # matches the unpadded slots/ts vectors
        owner[0] = "applied"
        events = wnd.apply_bitmap(
            p.bits_dev[: p.B], p.slots, p.ts_s, p.ts_ns, self.active_table,
            p.host_idx,
        )
        m_rows, m_bits = sparse()
        return FusedWindowsResult(
            events=events, matched_rows=m_rows, matched_bits=m_bits,
            always_bits=always_bits, bits_dev=p.bits_dev, pins_held=False,
        )


@dataclasses.dataclass
class FusedWindowsResult:
    """collect()'s outcome; see its docstring for the overflow taxonomy."""

    events: Optional[List[WindowEvent]]   # None: caller must re-apply
    matched_rows: Optional[np.ndarray]    # caller rows with >=1 stage2 bit
    matched_bits: Optional[np.ndarray]    # [len(matched_rows), nf8] packed
    always_bits: Optional[np.ndarray]     # [B, na8] packed always-rule bits
    bits_dev: object                      # dense device bitmap (may be None)
    pins_held: bool                       # True: caller owns the slot pins
