"""Fused matcher + device-windows pipeline, split into two device programs
so chunks can OVERLAP without ever reordering window updates.

Why fused at all: with device windows on, the naive path round-trips the
match bitmap through the host — the matcher pulls its sparse result down
(~65 ms fixed tunnel latency per pull), the runner rebuilds a dense
[B, n_rules] bitmap, and apply_bitmap pushes those ~16 MB back up for the
window scan. Here the dense caller-order bitmap never exists on the host.

Why two programs (PERF.md "path to 5M" 3c): a single fused program forces
strict chunk serialization — if chunk N overflows (its state writes gated
off), its classic re-apply would land on the device stream AFTER an
already-submitted chunk N+1, reordering window updates. Splitting fixes it:

  program A — MATCH (stateless): two-stage match (prefilter._match_core),
    dense caller-order bitmap assembly, and ALL overflow flags — candidate
    count, match-pair count, and the window-event count (it takes
    host_idx + active_table precisely so the event count is known before
    any state is touched). Outputs: one sparse host buffer (flags ‖
    (row, rule) match pairs ‖ always-rule bits) and the device-resident
    bitmap. A dispatches freely, any number of chunks ahead.

  program B — APPLY (window state donated): the window segmented scan
    (windows._apply_core) over A's bitmap. B for chunk i is dispatched
    only after chunk i's A-flags are known ok AND every earlier chunk's
    apply (B or classic fallback) has completed its dispatch — so
    device-stream order equals log order, always. Overflowing chunks never
    dispatch B: the caller drains all earlier chunks, then replays through
    the classic splitting path (state untouched, output identical).

Both pulls (A's sparse buffer, B's event buffer) are async and overlap
later chunks' compute, hiding the tunnel's fixed d2h latency.

Ordering machinery: submit() assigns a sequence number; resolve() and
collect() each gate on it (resolve order = B dispatch order = device apply
order; collect order = host-shadow write order). The shadow must absorb
batches in device-apply order or an eviction could restore stale counters.

Event order parity: bits are scattered into CALLER row order before the
window apply, so the event compaction's row-major (line, rule) order — the
reference's per-site-then-global processing order — is preserved exactly
as in the classic path.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import threading
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from banjax_tpu.matcher import windows as W
from banjax_tpu.obs import trace
from banjax_tpu.matcher.prefilter import FusedPrefilter
from banjax_tpu.matcher.windows import DeviceWindows, WindowEvent
from banjax_tpu.decisions.rate_limit import RateLimitMatchType

log = logging.getLogger(__name__)

_SHIFTS = (0, 8, 16, 24)


@dataclasses.dataclass
class _Pend:
    """One chunk in flight. States: submitted → resolved → done, or
    submitted → overflow → (caller fallback) → done."""

    seq: int
    sparse_buf: object     # program A's buffer (async pull in flight)
    bits_dev: object       # [Bp, n_rules] uint8 device-resident
    slots: np.ndarray      # caller-order, pins held
    ts_s: np.ndarray       # padded to Bp
    ts_ns: np.ndarray      # padded to Bp
    host_idx: np.ndarray   # padded to Bp
    B: int                 # real rows
    Bp: int
    K: int
    P: int
    state: str = "submitted"
    flags: Optional[np.ndarray] = None     # [4] after resolve
    events_buf: object = None              # program B's buffer
    # decoded at resolve (from the A pull)
    matched_pairs: Optional[np.ndarray] = None
    always_bits: Optional[np.ndarray] = None
    # transfer accounting (obs/stats.py note_xfer): what this chunk moved
    # across the host boundary — the fusion-win witness is the ABSENCE of
    # the dense [B, n_rules] bitmap from h2d_bytes
    h2d_bytes: int = 0
    d2h_bytes: int = 0


@dataclasses.dataclass
class FusedWindowsResult:
    """Outcome of one collected chunk."""

    events: List[WindowEvent]
    matched_pairs: Optional[np.ndarray]   # int32 caller_row * R8 + bit col
    always_bits: Optional[np.ndarray]     # [B, na8] packed always-rule bits


class PipelineOverflow(RuntimeError):
    """resolve() found an overflow: the caller must finish this chunk via
    the classic fallback (then call fallback_done)."""

    def __init__(self, candidate_overflow: bool):
        super().__init__(
            "candidate capacity exceeded" if candidate_overflow
            else "match-pair/event capacity exceeded"
        )
        # True: stage 2 never saw the excess lines — even the dense bitmap
        # is incomplete and must be recomputed single-stage
        self.candidate_overflow = candidate_overflow


class FusedWindowsPipeline:
    """Built by TpuMatcher when the fused prefilter and device windows are
    both active and every rule is device-decidable.

    Contract: submit in chunk order; resolve and collect each in that same
    order (they gate on it). Pins are owned by the pipeline from submit()
    until collect() completes — except after PipelineOverflow, where the
    caller's fallback apply (which releases them) takes over, followed by
    fallback_done() to release the order turns."""

    def __init__(self, prefilter: FusedPrefilter, windows: DeviceWindows,
                 active_table, n_rules: int):
        self.pf = prefilter
        self.windows = windows
        self.active_table = jnp.asarray(active_table)
        self.n_rules = n_rules
        self._match_fns = {}
        self._apply_fns = {}
        plan = prefilter.plan
        self._f_idx = jnp.asarray(plan.f_idx, dtype=jnp.int32)
        self._a_idx = jnp.asarray(plan.a_idx, dtype=jnp.int32)
        na = plan.n_always
        self._aw = jnp.asarray(
            np.asarray(plan.stage1.always_match[:na], dtype=np.uint8)
        )
        self._ae = jnp.asarray(
            np.asarray(plan.stage1.empty_only[:na], dtype=np.uint8)
        )
        self.fused_batches = 0
        self.fallback_batches = 0
        self._cv = threading.Condition()
        self._next_seq = 0      # assigned at submit
        self._resolve_seq = 0   # B-dispatch order
        self._collect_seq = 0   # shadow-write order
        # turns of chunks that died before taking them (resolve failure,
        # abandon): swept lazily when the counter reaches them — advancing
        # out of turn would steal an earlier live chunk's turn
        self._dead = {"_resolve_seq": set(), "_collect_seq": set()}

    # ---- program A: stateless match + flags ----

    def _match_prog(self, Bp: int, L_p: int):
        key = (Bp, L_p)
        hit = self._match_fns.get(key)
        if hit is not None:
            return hit
        pf = self.pf
        plan = pf.plan
        block, K = pf.capacities(Bp)
        core = pf._match_core(Bp, L_p, K, block)
        P = pf.pair_capacity(Bp, K)
        n_rules, n_filt = self.n_rules, plan.stage2.n_rules
        n_always = plan.n_always
        f_idx, a_idx = self._f_idx, self._a_idx
        aw, ae = self._aw, self._ae
        max_events = self.windows.max_events
        active_table = self.active_table
        shifts = jnp.asarray(_SHIFTS, dtype=jnp.int32)

        @jax.jit
        def match(combined, n_real, host_idx):
            c = core(combined)
            # sparse (row, rule) pair output — the shared encoding
            # (prefilter.pairs_from_core): one int32 per set stage-2 bit
            # instead of a packed row bitmap per matched line (~30x less
            # d2h on the tunnel, whose ~20-25 MB/s would otherwise
            # dominate the chunk budget). pair_bits doubles as the dense
            # per-candidate form for the bitmap assembly below.
            pairs, n_pairs, pair_bits = pf.pairs_from_core(c, K, P)
            # dense caller-order bitmap, assembled on device
            m2 = pair_bits[:, :n_filt].astype(jnp.uint8)         # [K, n_filt]
            filt = jnp.zeros((Bp + 1, n_filt), dtype=jnp.uint8)
            filt = filt.at[c["idx_caller_k"]].set(m2)[:Bp]       # row Bp = dump
            bits = jnp.zeros((Bp, n_rules), dtype=jnp.uint8)
            bits = bits.at[:, f_idx].set(filt)
            ab = None
            if n_always:
                ab = c["ab_caller"] | aw[None, :]
                empty = (c["lens_raw"] == 0).astype(jnp.uint8)[:, None]
                ab = ab | (ae[None, :] * empty)
                bits = bits.at[:, a_idx].set(ab)
            # padding rows (row >= n_real) can still carry bits — e.g. an
            # always_match rule's column is all-ones — and MUST NOT reach
            # the window apply: their pad slot id belongs to a real IP
            real = jax.lax.iota(jnp.int32, Bp) < n_real
            bits = bits * real[:, None].astype(jnp.uint8)
            # the window-event count, computed HERE so every overflow
            # condition is known before any state is touched
            fire = (bits != 0) & active_table[host_idx]
            n_events = fire.sum(dtype=jnp.int32)
            ok = (
                (c["n_cand"] <= K) & (n_pairs <= P)
                & (n_events <= max_events)
            )
            flags = jnp.stack([
                ok.astype(jnp.int32), c["n_cand"], n_pairs, n_events,
            ])
            parts = [
                ((flags[:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                ((pairs[:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
            ]
            if n_always:
                # sparse rows cover only the filterable rules; replay
                # bookkeeping needs the completed always-rule bits too
                parts.append(
                    jnp.packbits(ab.astype(jnp.bool_), axis=1).reshape(-1)
                )
            return jnp.concatenate(parts), bits

        self._match_fns[key] = (match, K, P)
        return match, K, P

    # ---- program B: window apply on a device-resident bitmap ----

    def _apply_prog(self, Bp: int):
        hit = self._apply_fns.get(Bp)
        if hit is not None:
            return hit
        wnd = self.windows
        n_rules = self.n_rules
        max_events = wnd.max_events
        limits, iv_s, iv_ns = wnd._limits, wnd._iv_s, wnd._iv_ns
        active_table = self.active_table
        shifts = jnp.asarray(_SHIFTS, dtype=jnp.int32)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def apply(state, bits, slots, ts_s, ts_ns, host_idx, live):
            # `live` gates rows that aged past the staleness cutoff while
            # queued in the streaming pipeline: the deferred commit drops
            # them HERE (a handful of bytes h2d) instead of re-uploading a
            # row-filtered dense bitmap
            bits = bits * live[:, None]
            new_state, ev = W._apply_core(
                state, bits, active_table, host_idx, slots, ts_s, ts_ns,
                limits, iv_s, iv_ns,
                n_rules=n_rules, max_events=max_events,
            )
            parts = [
                ((ev["line"][:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                ((ev["rule"][:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                ((ev["hits"][:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                ((ev["start_s"][:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                ((ev["start_ns"][:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
                ev["match_type"].astype(jnp.uint8),
                ev["exceeded"].astype(jnp.uint8),
                ev["seen_ip"].astype(jnp.uint8),
            ]
            return new_state, jnp.concatenate(parts)

        self._apply_fns[Bp] = apply
        return apply

    # ---- host API (submit → resolve → collect, each in chunk order) ----

    def submit(
        self, cls_ids: np.ndarray, lens: np.ndarray, slots: np.ndarray,
        ts_s: np.ndarray, ts_ns: np.ndarray, host_idx: np.ndarray,
    ) -> _Pend:
        """Dispatch program A for one chunk (slot pins held by the caller,
        ownership passes to the pipeline). Any number of chunks may be
        submitted ahead of their resolves."""
        pf = self.pf
        cls_ids = np.asarray(cls_ids, dtype=np.int32)
        lens = np.asarray(lens, dtype=np.int32)
        B = cls_ids.shape[0]
        combined, Bp, L_p = pf._assemble(cls_ids, lens)
        match, K, P = self._match_prog(Bp, L_p)

        def pad(a, fill=0):
            a = np.asarray(a)
            if Bp == len(a):
                return a
            return np.concatenate(
                [a, np.full(Bp - len(a), fill, dtype=a.dtype)]
            )

        host_idx_p = pad(host_idx).astype(np.int32)
        sparse_buf, bits_dev = match(
            jnp.asarray(combined), jnp.int32(B), jnp.asarray(host_idx_p)
        )
        try:
            sparse_buf.copy_to_host_async()
        except AttributeError:
            pass
        with self._cv:
            seq = self._next_seq
            self._next_seq += 1
        return _Pend(
            seq=seq, sparse_buf=sparse_buf, bits_dev=bits_dev,
            slots=np.asarray(slots),
            ts_s=pad(ts_s).astype(np.int32),
            ts_ns=pad(ts_ns).astype(np.int32),
            host_idx=host_idx_p, B=B, Bp=Bp, K=K, P=P,
            # the whole host→device traffic for this chunk: the encoded
            # class array + the per-row window metadata — crucially NOT a
            # dense [B, n_rules] bitmap
            h2d_bytes=combined.nbytes + 4 * 3 * Bp,
        )

    def _wait_turn(self, p: _Pend, attr: str) -> None:
        with self._cv:
            if getattr(self, attr) == p.seq:
                return
        # the drain thread blocking on an out-of-order turn is exactly
        # the stall a trace must show; the fast path above stays lock+
        # check only (the span records nothing when tracing is off)
        with trace.span("turn-wait", args={"seq": p.seq, "gate": attr}):
            with self._cv:
                while getattr(self, attr) != p.seq:
                    self._cv.wait()

    def _sweep_locked(self, attr: str, v: int) -> None:
        dead = self._dead[attr]
        while v in dead:
            dead.discard(v)
            v += 1
        setattr(self, attr, v)
        self._cv.notify_all()

    def _advance(self, attr: str) -> None:
        with self._cv:
            self._sweep_locked(attr, getattr(self, attr) + 1)

    def _mark_dead(self, attr: str, seq: int) -> None:
        """Free one order turn without requiring it to be current: dead
        turns are swept the moment the counter reaches them."""
        with self._cv:
            self._dead[attr].add(seq)
            self._sweep_locked(attr, getattr(self, attr))

    def abandon(self, p: _Pend) -> None:
        """Settle a chunk whose apply will never run (pipeline teardown,
        a failed submit burst, or a fully-stale chunk at drain): release
        its pins and both order turns. Safe for any not-yet-applied state —
        program A is stateless, so an abandoned chunk leaves no trace."""
        if p.state in ("done", "failed", "resolved"):
            return
        p.state = "failed"
        self.windows.release_pins(p.slots)
        self._mark_dead("_resolve_seq", p.seq)
        self._mark_dead("_collect_seq", p.seq)

    def idle(self) -> bool:
        """True when no submitted chunk is awaiting its apply/collect."""
        with self._cv:
            return self._next_seq == self._collect_seq

    def resolve(self, p: _Pend, live: Optional[np.ndarray] = None) -> None:
        """Order-gated: decode chunk p's A-flags; when ok, dispatch program
        B (the window apply) — B dispatches therefore happen strictly in
        chunk order. `live` (bool [B], default all-true) gates rows out of
        the window commit — the streaming pipeline's drain-time staleness
        drop composed with the deferred apply. Raises PipelineOverflow when
        the chunk must take the classic fallback; the resolve turn is NOT
        advanced until the caller completes the fallback (fallback_done),
        keeping later chunks' applies behind this chunk's."""
        self._wait_turn(p, "_resolve_seq")
        if p.state != "submitted":
            return
        try:
            buf = np.asarray(p.sparse_buf)
            p.d2h_bytes += buf.nbytes
            P = p.P
            R8 = self.pf._nf8 * 8
            flags = np.frombuffer(buf[:16].tobytes(), dtype="<i4")
            p.flags = flags
            off = 16
            pairs = np.frombuffer(
                buf[off : off + 4 * P].tobytes(), dtype="<i4"
            )
            off += 4 * P
            na8 = self.pf._na8
            p.always_bits = (
                buf[off:].reshape(-1, na8)[: p.B] if na8 else None
            )
            n_pairs = int(flags[2])
            if n_pairs <= P:
                live_pairs = pairs[:n_pairs]
                rows_idx = live_pairs // R8
                cols = live_pairs - rows_idx * R8
                # same invariant as prefilter.collect: row in range AND
                # col within the true rule count, so matched_pairs is a
                # clean invariant at the source (consumers may index f_idx
                # with it directly)
                keep = (
                    (rows_idx >= 0) & (rows_idx < p.B)
                    & (cols < self.pf.plan.stage2.n_rules)
                )
                p.matched_pairs = live_pairs[keep]
            if not flags[0]:
                p.state = "overflow"
                self.fallback_batches += 1
                raise PipelineOverflow(
                    candidate_overflow=int(flags[1]) > p.K
                )

            wnd = self.windows
            apply = self._apply_prog(p.Bp)
            slots_p = p.slots.astype(np.int32)
            if p.Bp != p.B:
                slots_p = np.concatenate(
                    [slots_p, np.zeros(p.Bp - p.B, dtype=np.int32)]
                )
            live_p = np.ones(p.Bp, dtype=np.uint8)
            if live is not None:
                live_p[: p.B] = np.asarray(live, dtype=np.uint8)
            p.h2d_bytes += live_p.nbytes
            with wnd._lock:
                wnd._run_maintenance_locked()
                new_state, ebuf = apply(
                    wnd._state, p.bits_dev, jnp.asarray(slots_p),
                    jnp.asarray(p.ts_s), jnp.asarray(p.ts_ns),
                    jnp.asarray(p.host_idx), jnp.asarray(live_p),
                )
                wnd._state = new_state
            try:
                ebuf.copy_to_host_async()
            except AttributeError:
                pass
            p.events_buf = ebuf
            p.state = "resolved"
            self.fused_batches += 1
        except PipelineOverflow:
            raise  # turns advance via fallback_done after the fallback
        except Exception:
            # the chunk is dead: free its order turns (a stuck turn would
            # deadlock every later resolve/collect forever) and the pins.
            # The resolve turn is held by this call (current == p.seq) so
            # _mark_dead advances it directly; the collect turn may still
            # belong to an EARLIER uncollected chunk and sweeps lazily.
            p.state = "failed"
            self.windows.release_pins(p.slots)
            self._mark_dead("_resolve_seq", p.seq)
            self._mark_dead("_collect_seq", p.seq)
            raise
        self._advance("_resolve_seq")

    def fallback_done(self, p: _Pend) -> None:
        """The caller's classic fallback for an overflowing chunk is fully
        applied (device + shadow + pins released by apply_bitmap): release
        both order turns."""
        p.state = "done"
        self._advance("_resolve_seq")
        self._advance("_collect_seq")

    def collect(self, p: _Pend) -> FusedWindowsResult:
        """Order-gated on the collect turn: decode chunk p's window events,
        absorb the final counter states into the host shadow, release the
        pins. Only valid for resolved chunks (collect() resolves first on
        the serial convenience path)."""
        if p.state == "submitted":
            self.resolve(p)  # may raise PipelineOverflow to the caller
        assert p.state == "resolved", p.state
        self._wait_turn(p, "_collect_seq")
        wnd = self.windows
        try:
            buf = np.asarray(p.events_buf)
            p.d2h_bytes += buf.nbytes
            me = wnd.max_events
            off = 0

            def take_i32(n):
                nonlocal off
                out = np.frombuffer(
                    buf[off : off + 4 * n].tobytes(), dtype="<i4"
                )
                off += 4 * n
                return out

            ev_line = take_i32(me)
            ev_rule = take_i32(me)
            ev_hits = take_i32(me)
            ev_ss = take_i32(me)
            ev_sns = take_i32(me)
            ev_mtype = buf[off : off + me]; off += me
            ev_exc = buf[off : off + me]; off += me
            ev_seen = buf[off : off + me]; off += me

            live = np.flatnonzero(ev_rule >= 0)
            events = [
                WindowEvent(
                    line=int(ev_line[k]),
                    rule_id=int(ev_rule[k]),
                    match_type=RateLimitMatchType(int(ev_mtype[k])),
                    exceeded=bool(ev_exc[k]),
                    seen_ip=bool(ev_seen[k]),
                )
                for k in live
            ]
            # shadow update mirrors _apply_bitmap_inner: (line, rule) order
            # so dict INSERTION order matches the reference's
            # first-matched-event order (format_states parity); last write
            # per (ip, rule) is still the chronologically-final state.
            # Collect order == apply order, so concurrent chunks can't
            # interleave stale values.
            from collections import OrderedDict

            shorder = np.lexsort((ev_rule[live], ev_line[live]))
            with wnd._lock:
                for k in live[shorder]:
                    ip = wnd._slot_ip.get(int(p.slots[int(ev_line[k])]))
                    if ip is None:
                        continue
                    od = wnd._shadow.setdefault(ip, OrderedDict())
                    od[int(ev_rule[k])] = (
                        int(ev_hits[k]), int(ev_ss[k]), int(ev_sns[k])
                    )
            events.sort(key=lambda e: (e.line, e.rule_id))
            p.state = "done"
            return FusedWindowsResult(
                events=events, matched_pairs=p.matched_pairs,
                always_bits=p.always_bits,
            )
        finally:
            wnd.release_pins(p.slots)
            self._advance("_collect_seq")
