"""Array-backed work batches for the TPU matcher's host path.

The reference's consumeLine walks one Go struct per line
(/root/reference/internal/regex_rate_limiter.go:126-157); a literal port
builds a Python object + several strings per line, which at 65k-line
batches costs ~300 ms — far more than the device match itself (the r3
end-to-end wall). This module keeps the batch COLUMNAR end to end:

  * `NativeWork` holds numpy row indices + unique-string tables from the
    native parse (banjax_tpu/native). Per-row Python objects materialize
    lazily, only for rows something actually touches — matched rows, ban
    logging, error paths — which is a few percent of traffic.
  * `ListWork` wraps the per-line-parsed fallback path (no native lib,
    deferred timestamps) in the same interface, so every consumer
    (window-slot scaffolding, the fused pipeline, replay) is agnostic.

The interface both provide:
  len(work); work[int] -> (orig_index, line); work[slice] -> same kind;
  iteration over (orig_index, line); unique_ips() -> (list[str], inverse);
  host_idx(host_row) -> np.int32 per row; ts_array() -> np.int64 per row.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Tuple

import numpy as np

from banjax_tpu.matcher.encode import ParsedLine


class LazyResults:
    """List-compatible ConsumeLineResult vector that materializes entries
    on first access. consume_lines must return one result per line, but
    production (cli._consume_lines) only reads them in debug mode — eager
    construction of 65k dataclasses per batch costs more than the whole
    vectorized gate."""

    __slots__ = ("_items", "_n_set")

    def __init__(self, n: int):
        self._items = [None] * n
        self._n_set = 0

    def __len__(self) -> int:
        return len(self._items)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[k] for k in range(*i.indices(len(self._items)))]
        r = self._items[i]
        if r is None:
            from banjax_tpu.matcher.api import ConsumeLineResult

            r = self._items[i] = ConsumeLineResult()
            self._n_set += 1
        return r

    def __iter__(self):
        for k in range(len(self._items)):
            yield self[k]

    def absorb(self, other: "LazyResults", row0: int) -> None:
        """Copy `other`'s MATERIALIZED entries in at row offset `row0`
        (the sharded-encode merge step); untouched rows stay lazy.  A
        shard of clean traffic materializes nothing during the gate —
        the counter makes that common case O(1) instead of a scan."""
        if other._n_set == 0:
            return
        dst = self._items
        for i, r in enumerate(other._items):
            if r is not None:
                dst[row0 + i] = r
        self._n_set += other._n_set


class LazyLine:
    """ParsedLine-compatible view over one native-parsed row.

    `rest` (the regex haystack, only needed for ban logging and host-regex
    fallback) decodes from the parse blob on first touch. `error`/
    `old_line` are class-level False: rows with either flag never enter a
    work set."""

    __slots__ = ("timestamp_ns", "ip", "host", "_nb", "_nbrow", "_rest")

    error = False
    old_line = False

    def __init__(self, nb, nbrow: int, ip: str, host: str, ts_ns: int):
        self.timestamp_ns = ts_ns
        self.ip = ip
        self.host = host
        self._nb = nb
        self._nbrow = nbrow
        self._rest = None

    @property
    def rest(self) -> str:
        if self._rest is None:
            self._rest = self._nb.rest(self._nbrow)
        return self._rest


class NativeWork:
    """(orig_index, line) sequence backed by the native ParsedBatch.

    `rows` are indices into the parse batch (== original line indices);
    `ip_inv`/`host_inv` index the shared unique-string tables. Slicing
    shares the tables (compaction happens in unique_ips, where a stale
    entry would otherwise leak a slot pin)."""

    __slots__ = (
        "nb", "rows", "ips_u", "ip_inv", "hosts_u", "host_inv", "ts_ns",
        "defer_map",
    )

    def __init__(self, nb, rows, ips_u, ip_inv, hosts_u, host_inv, ts_ns,
                 defer_map):
        self.nb = nb
        self.rows = rows                  # np.int64 [n] — nb/original rows
        self.ips_u: List[str] = ips_u
        self.ip_inv = ip_inv              # np.int64 [n] -> ips_u
        self.hosts_u: List[str] = hosts_u
        self.host_inv = host_inv          # np.int64 [n] -> hosts_u
        self.ts_ns = ts_ns                # np.int64 [n]
        # python-parsed lines for FLAG_DEFER rows, keyed by nb row
        self.defer_map: Dict[int, ParsedLine] = defer_map

    def __len__(self) -> int:
        return len(self.rows)

    def __getitem__(self, k):
        if isinstance(k, slice):
            return NativeWork(
                self.nb, self.rows[k], self.ips_u, self.ip_inv[k],
                self.hosts_u, self.host_inv[k], self.ts_ns[k],
                self.defer_map,
            )
        nbrow = int(self.rows[k])
        p = self.defer_map.get(nbrow)
        if p is None:
            p = LazyLine(
                self.nb, nbrow, self.ips_u[self.ip_inv[k]],
                self.hosts_u[self.host_inv[k]], int(self.ts_ns[k]),
            )
        return nbrow, p

    def __iter__(self):
        for k in range(len(self.rows)):
            yield self[k]

    def take(self, idx) -> "NativeWork":
        """Arbitrary-row subset (index array) — same table-sharing
        semantics as slicing; the pipeline's drain-time staleness filter
        uses this to drop aged-out rows before the window pass."""
        idx = np.asarray(idx, dtype=np.int64)
        return NativeWork(
            self.nb, self.rows[idx], self.ips_u, self.ip_inv[idx],
            self.hosts_u, self.host_inv[idx], self.ts_ns[idx],
            self.defer_map,
        )

    def unique_ips(self) -> Tuple[List[str], np.ndarray]:
        """(distinct ips present in THIS view, per-row inverse). Compacts
        the shared table so a slice never allocates (and pins) window
        slots for ips that aren't in it."""
        present, inv = np.unique(self.ip_inv, return_inverse=True)
        if present.size == len(self.ips_u):
            # unsliced view (or one covering every table entry): ids are
            # already compact — skip the per-entry re-list
            return self.ips_u, self.ip_inv
        ips_u = self.ips_u
        return [ips_u[j] for j in present.tolist()], inv

    def host_idx(self, host_row: Dict[str, int]) -> np.ndarray:
        tbl = np.asarray(
            [host_row.get(h, 0) for h in self.hosts_u], dtype=np.int32
        )
        return tbl[self.host_inv] if len(self.hosts_u) else np.zeros(
            len(self.rows), dtype=np.int32
        )

    def ts_array(self) -> np.ndarray:
        return self.ts_ns


class ListWork(list):
    """The [(orig_index, ParsedLine)] fallback path (python parse / no
    native lib) wearing the same interface as NativeWork."""

    def unique_ips(self) -> Tuple[List[str], np.ndarray]:
        uniq: "OrderedDict[str, int]" = OrderedDict()
        inv = np.empty(len(self), dtype=np.int64)
        for k, (_, p) in enumerate(self):
            j = uniq.get(p.ip)
            if j is None:
                j = len(uniq)
                uniq[p.ip] = j
            inv[k] = j
        return list(uniq), inv

    def host_idx(self, host_row: Dict[str, int]) -> np.ndarray:
        return np.asarray(
            [host_row.get(p.host, 0) for _, p in self], dtype=np.int32
        )

    def ts_array(self) -> np.ndarray:
        # Python float()*1e9 can exceed int64; clamp exactly like the
        # native gate does for deferred rows — the columnar array only
        # feeds the device windows, while replay reads the exact Python
        # int from the ParsedLine
        lo, hi = -(2**63), 2**63 - 1
        return np.asarray(
            [min(max(p.timestamp_ns, lo), hi) for _, p in self],
            dtype=np.int64,
        )

    def __getitem__(self, k):
        if isinstance(k, slice):
            return ListWork(super().__getitem__(k))
        return super().__getitem__(k)

    def take(self, idx) -> "ListWork":
        """Arbitrary-row subset (index array) — NativeWork.take parity."""
        return ListWork(list.__getitem__(self, int(i)) for i in idx)


class CompositeWork:
    """Strict line-order concatenation of per-shard work sets — the merge
    half of the sharded encode pool (pipeline/scheduler.py).

    Each part is a NativeWork/ListWork built over ONE contiguous row
    shard of the admission batch; `offsets[j]` is the batch row its
    shard started at.  Indices surfaced to consumers — the (orig_index,
    line) pairs, and therefore results rows, window-event lines, and
    replay order — are GLOBAL batch rows, so every downstream consumer
    (slot scaffolding, the fused pipeline, replay, staleness take) is
    agnostic to whether the encode ran sharded or single-threaded.

    unique_ips() merges the per-shard first-appearance tables in shard
    order, which IS global first-appearance order over the kept rows —
    the property window-slot LRU assignment order (a parity surface)
    depends on.  Positional subsets (slice/take) expect ascending
    indices, which is what every caller passes (chunking, staleness
    keep-masks, binary splits)."""

    __slots__ = ("parts", "offsets", "_starts")

    def __init__(self, parts: List, offsets: List[int]):
        self.parts = parts        # non-empty work sets, shard order
        self.offsets = offsets    # first batch row of each part's shard
        self._starts = np.cumsum([0] + [len(w) for w in parts])

    def __len__(self) -> int:
        return int(self._starts[-1])

    def __getitem__(self, k):
        if isinstance(k, slice):
            return self.take(
                np.arange(*k.indices(len(self)), dtype=np.int64)
            )
        j = int(np.searchsorted(self._starts, k, side="right")) - 1
        i, p = self.parts[j][k - int(self._starts[j])]
        return self.offsets[j] + i, p

    def __iter__(self):
        for j, w in enumerate(self.parts):
            off = self.offsets[j]
            for i, p in w:
                yield off + i, p

    def take(self, idx) -> "CompositeWork | ListWork":
        idx = np.asarray(idx, dtype=np.int64)
        parts: List = []
        offsets: List[int] = []
        for j, w in enumerate(self.parts):
            lo, hi = int(self._starts[j]), int(self._starts[j + 1])
            sel = idx[(idx >= lo) & (idx < hi)] - lo
            if sel.size:
                parts.append(w.take(sel))
                offsets.append(self.offsets[j])
        if not parts:
            return ListWork()
        if len(parts) == 1 and offsets[0] == 0:
            return parts[0]
        return CompositeWork(parts, offsets)

    def unique_ips(self) -> Tuple[List[str], np.ndarray]:
        merged: Dict[str, int] = {}
        strings: List[str] = []
        invs = []
        for w in self.parts:
            ips_u, inv = w.unique_ips()
            remap = np.empty(len(ips_u), dtype=np.int64)
            for j, s in enumerate(ips_u):
                g = merged.get(s)
                if g is None:
                    g = len(strings)
                    merged[s] = g
                    strings.append(s)
                remap[j] = g
            invs.append(remap[np.asarray(inv, dtype=np.int64)])
        return strings, np.concatenate(invs)

    def host_idx(self, host_row: Dict[str, int]) -> np.ndarray:
        return np.concatenate([w.host_idx(host_row) for w in self.parts])

    def ts_array(self) -> np.ndarray:
        return np.concatenate([w.ts_array() for w in self.parts])


def unique_spans(
    offs: np.ndarray, lens: np.ndarray, decode,
    blob: "bytes | None" = None, text: "str | None" = None,
    dedup_scratch=None,
) -> Tuple[List[str], np.ndarray]:
    """Distinct-string extraction over (offset, length) spans of a blob.

    Fast path (native lib + `blob`): C open-addressing dedup
    (fastparse.c fp_dedup_spans) emits first-appearance-ordered ids
    directly; unique strings slice out of the ASCII `text` in one comp.
    Fallback (native lib failed to load mid-flight — the gate itself only
    runs with it loaded, so this is belt-and-braces): exact per-row dict
    dedup over decoded strings, trivially correct and first-appearance
    ordered. Returns (unique strings, per-row inverse)."""
    n = len(offs)
    if n == 0:
        return [], np.zeros(0, dtype=np.int64)
    if blob is not None:
        from banjax_tpu import native as _native

        df = _native.dedup_spans(blob, offs, lens, dedup_scratch)
        if df is not None:
            ids, first = df
            if text is not None:
                # tolist() first: per-item numpy-scalar -> int conversions
                # cost more than the slices themselves at 65k uniques
                ot = offs.tolist()
                lt = lens.tolist()
                strings = [
                    text[ot[r] : ot[r] + lt[r]] for r in first.tolist()
                ]
            else:
                strings = [decode(int(r)) for r in first]
            return strings, ids
    seen: Dict[str, int] = {}
    strings: List[str] = []
    inv = np.empty(n, dtype=np.int64)
    for r in range(n):
        s = decode(r)
        j = seen.get(s)
        if j is None:
            j = len(strings)
            strings.append(s)
            seen[s] = j
        inv[r] = j
    return strings, inv
