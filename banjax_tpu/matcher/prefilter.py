"""Two-stage literal-prefiltered matching (Hyperscan's decomposition, TPU-shaped).

The single-stage matcher scans every byte of every line against the full
ruleset NFA — cost ∝ total NFA width, even though almost all traffic matches
nothing. Production literal matchers (Hyperscan FDR/Teddy) exploit that: a
cheap literal scan gates the expensive automaton. This module is that
architecture built from the pieces this repo already has:

  stage 1 (every line): one packed NFA containing (a) the rules that have no
    required literal factor — they must always run — and (b) one *factor
    automaton* per distinct required literal (rulec.required_factors: a run
    of narrow byte classes every match of the branch must contain). This NFA
    is ~10x narrower than the full ruleset's, so the scan is ~10x cheaper.
  stage 2 (candidate lines only): the full NFA of the filterable rules, run
    only on lines where at least one factor hit. Benign traffic rarely
    contains attack-rule literals, so stage 2 typically sees a few percent
    of lines.

Soundness: factor absent ⟹ branch cannot match (rulec.required_factors),
so gating on "any factor hit" never drops a true match — the combined
bitmap is bit-identical to the single-stage matcher's, which the
differential tests assert.

Both stages reuse the same Pallas kernel / XLA scan and the same packing
(rulec.pack_programs); the prefilter is a compile-time rearrangement of the
ruleset, not new device code.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from banjax_tpu.matcher import nfa_jax
from banjax_tpu.matcher.encode import classify_bytes, encode_lines
from banjax_tpu.matcher.kernels import nfa_match
from banjax_tpu.matcher.rulec import (
    CompiledRules,
    Pos,
    RuleProgram,
    UnsupportedPattern,
    compile_rule,
    factor_program,
    pack_programs,
    required_factors,
)

log = logging.getLogger(__name__)

_MIN_BUCKET = 64


@dataclasses.dataclass
class PrefilterPlan:
    """Compile-time split of a ruleset into the two stage automata."""

    n_rules: int
    stage1: CompiledRules        # always-rules ++ literal factor automata
    n_always: int                # first n_always stage-1 columns are rules...
    a_idx: np.ndarray            # ...these original rule ids
    n_factors: int               # remaining stage-1 columns are factors
    stage2: CompiledRules        # filterable rules
    f_idx: np.ndarray            # stage-2 column -> original rule id
    unsupported: Dict[int, str]  # rule id -> reason (host regex fallback)


def gate_masks(plan: "PrefilterPlan", prep=None):
    """Stage-1 gate arrays over the RAW accept words: (fmask [W1] uint32 —
    OR of all factor branches' accept bits; a_word/a_mask/a_rule — the
    always-rule branches' extraction triple). With `prep` (a PallasRules),
    word indices live in the kernel's padded word space. Shared by the
    single-device FusedPrefilter and the mesh fused path."""
    s1 = plan.stage1
    if prep is not None:
        w1 = prep.total_words
        acc_word = np.asarray(prep.acc_word)
    else:
        w1 = s1.n_words
        acc_word = np.asarray(s1.acc_word)
    acc_mask = np.asarray(s1.acc_mask, dtype=np.uint32)
    branch_rule = np.asarray(s1.branch_rule)
    fac = branch_rule >= plan.n_always
    fmask = np.zeros(w1, dtype=np.uint32)
    np.bitwise_or.at(fmask, acc_word[fac], acc_mask[fac])
    return (
        fmask,
        acc_word[~fac].astype(np.int32),
        acc_mask[~fac],
        branch_rule[~fac].astype(np.int32),
    )


# Bytes that dominate real log-line traffic (lowercase, digits, and URL /
# header punctuation). _pos_prob weighs a byte class's hit probability by
# how much of this set it covers: an exact lowercase byte scores ~1/48, a
# merged [a-p] class ~16/48 — the units only matter relative to the
# sel_max budget in _merge_factors.
_COMMON_BYTES = (
    bytes(range(0x61, 0x7B)) + bytes(range(0x30, 0x3A)) + b"/.-_ :=?&%"
)
_COMMON_MASK = 0
for _b in _COMMON_BYTES:
    _COMMON_MASK |= 1 << _b


def _pos_prob(cs: int) -> float:
    """Estimated probability that one benign-traffic byte lands in `cs`.

    The denominator is an *effective alphabet* of ~20, not 256: log-line
    text is mostly lowercase/digit/URL-punctuation with strongly skewed
    frequencies, so a k-byte class is hit far more often than k/256. The
    estimate only has to be conservative enough for the sel_max guard —
    measured candidate rates (bench's prefilter_gate_fraction) are the
    ground truth."""
    common = bin(cs & _COMMON_MASK).count("1")
    rare = bin(cs).count("1") - common
    return min(1.0, (common + 0.25 * rare) / 20.0)


def _merge_factors(
    factors: List[Tuple],
    max_merge: int = 16,
    sel_max: float = 1e-5,
) -> List[Tuple]:
    """Teddy-style factor superimposition: OR byte-similar *equal-length*
    factors position-wise into one shared automaton (Hyperscan's Teddy
    buckets several literals into one PSHUFB mask set the same way).

    Soundness: each member's class is a subset of the merged class at
    every position, so "merged automaton missed" still implies "no member
    factor present" — the stage-1 gate never drops a true match; merging
    can only raise the candidate rate, which stage 2 pays for and the
    differential tests continuously verify end-to-end.

    Only equal-length factors merge. An earlier variant truncated
    different-length factors to their common prefix; truncation destroys
    selectivity (a bucket cut to "GET /[a-z]…" fires on most traffic —
    measured: candidate rate 12.7 % vs the 4.1 % no-merge floor on the
    bench workload). Equal-length superimposition measured *zero* added
    candidates on the same workload (4.08 % either way) while shrinking
    stage-1 words 572 → 37 (15×) — and stage 1 is the scan-bound
    automaton run on EVERY line (PERF.md: VPU-scan-bound, cost ∝ words),
    so the fused-path win is near-linear. The `sel_max` budget is the
    general-workload guard: a bucket stops absorbing factors once its
    estimated per-start-offset benign hit probability (∏ _pos_prob)
    exceeds it (wide (?i) case-class merges hit this long before
    max_merge)."""
    if max_merge <= 1:
        return factors

    def sort_key(f):
        # length first (only equal lengths may merge), then the lowest
        # member byte per position: lexicographic order clusters
        # shared-prefix literals ("admin-login"/"admin-setup") together
        return (len(f),) + tuple((p.cs & -p.cs).bit_length() for p in f)

    out: List[List[int]] = []
    cur: Optional[List[int]] = None
    cur_n = 0
    for f in sorted(factors, key=sort_key):
        cs_list = [p.cs for p in f]
        if cur is not None and cur_n < max_merge and len(cs_list) == len(cur):
            merged = [cur[i] | cs_list[i] for i in range(len(cur))]
            sel = 1.0
            for c in merged:
                sel *= _pos_prob(c)
            if sel <= sel_max:
                cur, cur_n = merged, cur_n + 1
                continue
        if cur is not None:
            out.append(cur)
        cur, cur_n = cs_list, 1
    if cur is not None:
        out.append(cur)
    return [tuple(Pos(c) for c in cs) for cs in out]


def build_plan(
    patterns: Sequence[str],
    min_factor_len: int = 3,
    max_factor_len: int = 12,
    min_filterable_fraction: float = 0.5,
    byte_classes=None,
    stage2_shards="auto",
    factor_merge: int = 16,
    factor_sel_max: float = 1e-5,
) -> Optional[PrefilterPlan]:
    """Split `patterns` into the two-stage plan, or None when the ruleset
    doesn't profit (too few filterable rules — the two-pass overhead would
    outweigh the narrower stage 1).

    `byte_classes` = (byte_to_class, n_classes) of the full single-stage
    ruleset: both stage tensors are then packed against that shared byte
    partition, so one `classify_bytes` pass (or the native parse's encode)
    feeds stage 1, stage 2, AND the single-stage fallback — the layout
    contract of FusedPrefilter."""
    programs: List[Optional[RuleProgram]] = []
    unsupported: Dict[int, str] = {}
    for i, pat in enumerate(patterns):
        try:
            programs.append(compile_rule(pat))
        except UnsupportedPattern as e:
            programs.append(None)
            unsupported[i] = str(e)

    distinct_factors: Dict[Tuple, Tuple] = {}
    always_ids: List[int] = []
    filt_ids: List[int] = []
    for i, prog in enumerate(programs):
        if prog is None:
            continue  # host regex fallback, not on device at all
        factors = required_factors(
            prog, min_len=min_factor_len, max_len=max_factor_len
        )
        if factors is None:
            always_ids.append(i)
            continue
        filt_ids.append(i)
        for f in factors:
            distinct_factors.setdefault(tuple(p.cs for p in f), f)
    merged = _merge_factors(
        list(distinct_factors.values()),
        max_merge=factor_merge,
        sel_max=factor_sel_max,
    )
    factor_progs = [factor_program(f) for f in merged]

    n_device = len(always_ids) + len(filt_ids)
    if (
        n_device == 0
        or not factor_progs
        or len(filt_ids) < n_device * min_filterable_fraction
    ):
        return None

    stage1_programs = [programs[i] for i in always_ids] + factor_progs
    stage2_programs = [programs[i] for i in filt_ids]
    # stage 1 is the scan-bound hot automaton: word-align its branches so
    # the kernel drops the cross-word carry (factors are 3-12 positions, so
    # alignment costs little padding and carry_free always holds for them)
    s1 = pack_programs(
        stage1_programs, n_shards="auto", byte_classes=byte_classes,
        align_branches=True,
    )
    # stage2_shards=rp pins the word slabs to a mesh's rule-parallel axis
    s2 = pack_programs(
        stage2_programs, n_shards=stage2_shards, byte_classes=byte_classes
    )
    log.info(
        "prefilter plan: %d always + %d filterable rules, %d distinct "
        "factors in %d superimposed buckets; stage1 %d words, stage2 %d "
        "words",
        len(always_ids), len(filt_ids), len(distinct_factors),
        len(factor_progs), s1.n_words, s2.n_words,
    )
    return PrefilterPlan(
        n_rules=len(patterns),
        stage1=s1,
        n_always=len(always_ids),
        a_idx=np.asarray(always_ids, dtype=np.int64),
        n_factors=len(factor_progs),
        stage2=s2,
        f_idx=np.asarray(filt_ids, dtype=np.int64),
        unsupported=unsupported,
    )


class PrefilterMatcher:
    """Executable two-stage pipeline over a PrefilterPlan.

    backend: "pallas" | "pallas-interpret" | "xla" — same meanings as the
    runner's matcher_backend resolution.
    """

    def __init__(self, plan: PrefilterPlan, backend: str, max_len: int,
                 max_batch: int = 16384):
        self.plan = plan
        self.max_len = max_len
        self.max_batch = max(_MIN_BUCKET, max_batch)
        self.backend = backend
        self.interpret = backend == "pallas-interpret"
        self._preps = {}
        if backend in ("pallas", "pallas-interpret"):
            self._preps = {
                "s1": nfa_match.prepare(plan.stage1),
                "s2": nfa_match.prepare(plan.stage2),
            }
        else:
            self._params = {
                "s1": nfa_jax.match_params(plan.stage1),
                "s2": nfa_jax.match_params(plan.stage2),
            }

    def _run_stage(self, which: str, compiled: CompiledRules,
                   cls_ids: np.ndarray, lens: np.ndarray) -> np.ndarray:
        """[N, n_cols] uint8 match bits for one stage, bucketed/padded."""
        n = len(lens)
        out = np.zeros((n, compiled.n_rules), dtype=np.uint8)
        for start in range(0, n, self.max_batch):
            stop = min(n, start + self.max_batch)
            b = _bucket(stop - start, self.max_batch)
            pad_cls = np.zeros((b, cls_ids.shape[1]), dtype=np.int32)
            pad_len = np.zeros(b, dtype=np.int32)
            pad_cls[: stop - start] = cls_ids[start:stop]
            pad_len[: stop - start] = lens[start:stop]
            if self._preps:
                packed = nfa_match.match_batch_pallas(
                    self._preps[which], pad_cls, pad_len,
                    interpret=self.interpret, packed=True,
                )
            else:
                import jax.numpy as jnp  # local: keep module import light

                packed = np.asarray(
                    nfa_jax.match_batch_packed(
                        self._params[which], jnp.asarray(pad_cls),
                        jnp.asarray(pad_len), compiled.n_rules,
                    )
                )
            out[start:stop] = np.unpackbits(
                packed, axis=1, count=compiled.n_rules
            )[: stop - start]
        return out

    def match_bits(
        self, rests: Sequence[str]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """([N, n_rules] uint8 device-decided bits, [N] bool host_eval).

        host_eval rows (non-ASCII / over-long) carry all-zero bits; rules in
        plan.unsupported carry all-zero columns — the caller routes both to
        its host regex fallback exactly as for the single-stage matcher.
        """
        plan = self.plan
        bits = np.zeros((len(rests), plan.n_rules), dtype=np.uint8)

        bytes_mat, lens, host_eval = encode_lines(rests, self.max_len)
        rows = np.flatnonzero(~host_eval)
        if rows.size == 0:
            return bits, host_eval
        cls1 = classify_bytes(plan.stage1, bytes_mat[rows], lens[rows])
        s1 = self._run_stage("s1", plan.stage1, cls1, lens[rows])
        if plan.n_always:
            bits[np.ix_(rows, plan.a_idx)] = s1[:, : plan.n_always]

        cand_local = np.flatnonzero(s1[:, plan.n_always :].any(axis=1))
        if cand_local.size:
            cand_rows = rows[cand_local]
            cls2 = classify_bytes(
                plan.stage2, bytes_mat[cand_rows], lens[cand_rows]
            )
            s2 = self._run_stage("s2", plan.stage2, cls2, lens[cand_rows])
            bits[np.ix_(cand_rows, plan.f_idx)] = s2
        return bits, host_eval


def _bucket(n: int, cap: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return min(b, max(cap, _MIN_BUCKET))


class PrefilterOverflow(RuntimeError):
    """More stage-1 candidates than the fused pipeline's fixed capacity —
    the caller must rerun the batch through its single-stage path."""


@dataclasses.dataclass
class _Pending:
    """An in-flight fused batch: device buffer + host-order bookkeeping."""

    buf: object          # device array, copy_to_host_async already started
    B: int               # caller rows
    K: int               # candidate capacity
    P: int               # (row, rule) pair output capacity
    lens: np.ndarray     # caller-order lens (for empty_only always-rules)
    h2d_bytes: int = 0   # transfer accounting (obs/stats.py note_xfer)
    d2h_bytes: int = 0


class FusedPrefilter:
    """Single-jit two-stage pipeline: both stages, the candidate gate, the
    on-device compaction, and the bitmap merge run in ONE device program.

    The host-orchestrated PrefilterMatcher pays a device→host round trip
    plus a re-encode between the stages; on hardware that host work costs
    ~20x the kernels themselves (BENCH r3 scratch: 19.7k lines/s fused-host
    vs 497k single-stage). Here stage 1's candidate vector never leaves the
    device: `nonzero(size=K)` compacts the candidate lines' already-resident
    class columns, stage 2 scans only those, and the per-stage bits scatter
    back into one packed [B, ceil(R/8)] bitmap. Requires a plan built with
    `byte_classes` of the caller's full ruleset so the caller's encode (or
    native fastparse output) is consumed verbatim.

    Capacity: K = max(block, ceil(B * cand_frac)) compacted lines, and
    P = ceil(B * pair_frac) output (row, rule) pairs — pair_frac budgets
    PAIRS PER CALLER LINE, not a matched-row fraction of K (the r3 sparse
    rewrite changed the output encoding; the knob was renamed with it).
    Both counts come back with the result; exceeding either raises
    PrefilterOverflow (soundness: a truncated candidate or pair set would
    silently under-match) and the caller reruns that batch single-stage —
    an adversarial all-matching stream degrades to the single-stage rate,
    never to wrong output.
    """

    def __init__(self, plan: PrefilterPlan, backend: str,
                 cand_frac: float = 0.125, pair_frac: float = 0.25,
                 block_b: int = 0, cols: int = 0):
        """Chunking is the CALLER's job: submit() compiles one device
        program for exactly the batch shape it is handed (TpuMatcher
        chunks by its matcher_batch_lines before submitting)."""
        if plan.stage1.n_classes != plan.stage2.n_classes:
            raise ValueError("fused plan requires shared byte classes")
        self.plan = plan
        self.backend = backend
        self.interpret = backend == "pallas-interpret"
        self.cand_frac = cand_frac
        self.pair_frac = pair_frac
        self._pallas = backend in ("pallas", "pallas-interpret")
        if self._pallas:
            self._preps = {
                "s1": nfa_match.prepare(plan.stage1),
                "s2": nfa_match.prepare(plan.stage2),
            }
            # block 512 × cols 32 is the VMEM sweet spot on v5e: wider
            # blocks OOM the 16 MB scoped-vmem limit once the per-plane dot
            # transients and the double-buffered out block are counted
            self._block = block_b or (8 if self.interpret else 512)
            self._cols = cols or (8 if self.interpret else 32)
        else:
            self._params = {
                "s1": nfa_jax.match_params(plan.stage1),
                "s2": nfa_jax.match_params(plan.stage2),
            }
            self._block = block_b or 8
            self._cols = cols or 8
        self._fns = {}
        # pack 4 class ids per int32 for the h2d when the partition fits a
        # byte (it essentially always does: <=257 distinct classes exist
        # and real rulesets use ~100); little-endian lane order, so gate on
        # the host byte order too
        import sys as _sys

        self._pack_input = (
            plan.stage1.n_classes <= 256 and _sys.byteorder == "little"
        )

        # Stage-1 gate masks over the RAW accept words — the per-line
        # "any factor hit" bit needs no branch extraction at all (the
        # [B, n_branches] gather costs more than the stage-1 scan itself).
        s1 = plan.stage1
        fmask, a_word, a_mask, a_rule = gate_masks(
            plan, self._preps["s1"] if self._pallas else None
        )
        self._fmask = jnp.asarray(fmask)
        # always-rule extraction (usually a handful of branches)
        self._a_word = jnp.asarray(a_word)
        self._a_mask = jnp.asarray(a_mask)
        self._a_rule = jnp.asarray(a_rule)
        # host-static flags for always-rules (applied after decode)
        self._a_always = np.asarray(s1.always_match[: plan.n_always], dtype=bool)
        self._a_empty = np.asarray(s1.empty_only[: plan.n_always], dtype=bool)
        self._nf8 = -(-plan.stage2.n_rules // 8)
        self._na8 = -(-plan.n_always // 8) if plan.n_always else 0

    # ---- device program ----

    def _stage1_raw(self, B: int, L_p: int, block: int):
        """[L_p, B] cls + [1, B] lens → raw accept words [W1, B] uint32."""
        if self._pallas:
            prep = self._preps["s1"]
            call = nfa_match._build_raw_call(
                B, L_p, prep.n_classes_p, prep.n_shards, prep.wps_p, block,
                self.interpret, self._cols,
                carry=not prep.carry_free,
            )
            btab, masks = prep.btab_t, prep.masks_t
            cols = self._cols

            def fn(cls_t, lens):
                maxtile = -(-lens.reshape(B // block, block).max(axis=1) // cols)
                return call(
                    maxtile.astype(jnp.int32), cls_t, lens[None, :], btab, masks
                )

            return fn
        params = self._params["s1"]

        def xla_fn(cls_t, lens):
            return nfa_jax.nfa_scan(params, cls_t.T, lens).T  # [W1, B]

        return xla_fn

    def _stage2(self, K: int, L_p: int, block: int):
        """[L_p, K] cls + [K] lens → [K, nf8] packed match bits."""
        if self._pallas:
            return nfa_match.device_matcher(
                self._preps["s2"], K, L_p, block, interpret=self.interpret,
                pack=True, cols=self._cols,
            )
        params = self._params["s2"]
        n_filt = self.plan.stage2.n_rules

        def xla_fn(cls_t, lens):
            return nfa_jax.match_batch_packed(params, cls_t.T, lens, n_filt)

        return xla_fn

    def _block_for(self, B: int) -> int:
        """Largest usable line-block: compiled Mosaic requires a lane
        multiple (128), interpret/XLA just need block <= B."""
        if self._pallas and not self.interpret:
            return self._block if B >= self._block else 128
        return min(self._block, max(1, B))

    def _row_bucket(self, B: int) -> int:
        """Power-of-two-growth row bucket that _block_for(Bp) always
        divides (a compiled Mosaic grid floor-divides by the block, so a
        non-divisible pad would silently skip the tail). Production tail
        chunks vary freely and every distinct (Bp, L_p) is a full device
        program compile (~30 s of Mosaic on TPU); the bucket bounds
        lifetime variants to ~log2(max_batch / block). Pad rows carry
        lens=0, so the kernel's tile skip makes them near-free."""
        if self._pallas and not self.interpret:
            Bp = 128
            while Bp < B:
                Bp <<= 1
            if Bp >= self._block:
                # once past the configured block, grow FROM it so the
                # derived block (self._block, possibly a non-pow2 lane
                # multiple like 384) divides Bp by construction
                Bp = self._block
                while Bp < B:
                    Bp <<= 1
            return Bp
        Bp = _MIN_BUCKET
        while Bp < B:
            Bp <<= 1
        return Bp

    def _assemble(self, cls_ids: np.ndarray, lens: np.ndarray):
        """→ (combined [Bp, 1 + L4|L_p] int32, Bp, L_p): the one-transfer
        input layout of _match_core (col 0 = lens; class ids packed 4 per
        int32 when the partition fits uint8)."""
        B = cls_ids.shape[0]
        Bp = self._row_bucket(max(1, B))
        block = self._block_for(Bp)
        # L_p variants are already bounded by a CONSTANT: multiples of 32
        # up to the caller's fixed matcher_max_line_len (<= max_len/32 of
        # them) — no pow2 rounding, which would scan up to 2x the bytes on
        # every batch
        cols = self._cols
        max_len = int(lens.max()) if B else 0
        L_p = max(cols, min(
            -(-cls_ids.shape[1] // cols) * cols,
            -(-max(1, max_len) // max(32, cols)) * max(32, cols),
        ))
        Lc = min(cls_ids.shape[1], L_p)
        if self._pack_input:
            L4 = -(-L_p // 4)
            combined = np.zeros((Bp, 1 + L4), dtype=np.int32)
            if B:
                combined[:B, 0] = lens
                # write class ids straight into combined's byte view (LE
                # lanes; bytes 0-3 of each row are the lens int32) — no
                # intermediate buffer, one 4x-smaller copy total
                v = combined.view(np.uint8).reshape(Bp, (1 + L4) * 4)
                v[:B, 4 : 4 + Lc] = cls_ids[:, :Lc]
        else:
            combined = np.zeros((Bp, 1 + L_p), dtype=np.int32)
            if B:
                combined[:B, 0] = lens
                combined[:B, 1 : 1 + Lc] = cls_ids[:, :Lc]
        return combined, Bp, L_p

    def capacities(self, B: int):
        """(block, K candidate slots) for a batch."""
        block = self._block_for(B)
        K = min(B, max(block, -(-int(B * self.cand_frac) // block) * block))
        return block, K

    def pair_capacity(self, B: int, K: int) -> int:
        """Output slots for the sparse (row, rule) pair encoding: one int32
        per set rule bit, budgeted at `pair_frac` pairs per caller line and
        capped by the true maximum (every candidate matching every rule)."""
        if B * self._nf8 * 8 >= 2**31:
            raise ValueError(
                f"batch {B} x {self._nf8 * 8} packed rule columns overflows "
                "the int32 (row, rule) pair encoding — lower "
                "matcher_batch_lines"
            )
        return min(max(128, int(B * self.pair_frac)), K * self.plan.stage2.n_rules)

    def pairs_from_core(self, c, K: int, P: int):
        """The sparse (row, rule) pair extraction shared by the plain fused
        program and the fused-windows program A: one int32 per set stage-2
        bit, encoded caller_row * R8 + packed bit column (R8 = 8 * nf8),
        -1 beyond n_pairs. Returns (pairs [P] int32, n_pairs, bits [K, R8])
        — `bits` is the unpacked MSB-first bit tensor so callers needing
        the per-candidate dense form don't unpack m2p twice."""
        R8 = self._nf8 * 8
        bits = (
            (c["m2p"][:, :, None] >> (7 - jnp.arange(8, dtype=jnp.int32))) & 1
        ).reshape(K, R8)
        # mask pad columns beyond the true rule count: n_pairs and the pair
        # stream must be bounded by n_rules even if a packer left a pad bit
        # set (otherwise a stray pad bit inflates n_pairs toward spurious
        # PrefilterOverflow)
        bits = jnp.where(
            jnp.arange(R8, dtype=jnp.int32) < self.plan.stage2.n_rules,
            bits, 0,
        )
        n_pairs = jnp.sum(bits, dtype=jnp.int32)
        (flat,) = jnp.nonzero(bits.reshape(-1), size=P, fill_value=0)
        k = flat // R8
        col = flat - k * R8
        caller = jnp.take(c["idx_caller_k"], k)
        live = jax.lax.iota(jnp.int32, P) < n_pairs
        pairs = jnp.where(live, caller * R8 + col, -1)
        return pairs, n_pairs, bits

    def _match_core(self, B: int, L_p: int, K: int, block: int):
        """The traceable two-stage match body, shared by the sparse-output
        fused program and the fused matcher+windows pipeline
        (matcher/fused_windows.py). Input: [B, 1 + L4|L_p] int32 combined
        array (column 0 = lens; class row packed 4 uint8 ids per int32 when
        the partition fits a byte — see submit()). Returns every
        intermediate a consumer needs: the candidate count, the stage-2
        packed rows with their caller-row mapping (feed pairs_from_core
        for the sparse output), and the always-rule bits in caller row
        order."""
        plan = self.plan
        f1 = self._stage1_raw(B, L_p, block)
        f2 = self._stage2(K, L_p, min(block, K))
        n_always = plan.n_always
        fmask = self._fmask
        a_word, a_mask, a_rule = self._a_word, self._a_mask, self._a_rule
        shifts = jnp.asarray([0, 8, 16, 24], dtype=jnp.int32)
        packed_in = self._pack_input
        L4 = -(-L_p // 4)

        def core(cls_and_lens):
            lens_raw = cls_and_lens[:, 0]                        # [B]
            if packed_in:
                words = cls_and_lens[:, 1 : 1 + L4]              # [B, L4]
                cls_rows = (
                    (words[:, :, None] >> shifts[None, None, :]) & 0xFF
                ).reshape(words.shape[0], L4 * 4)[:, :L_p]
            else:
                cls_rows = cls_and_lens[:, 1 : 1 + L_p]          # [B, L_p]
            order = jnp.argsort(lens_raw)                        # ascending
            lens = jnp.take(lens_raw, order)
            cls_t = jnp.take(cls_rows, order, axis=0).T          # [L_p, B]
            acc1 = f1(cls_t, lens)                               # [W1, B]
            cand = (acc1 & fmask[:, None]).max(axis=0) > 0       # [B]
            n_cand = jnp.sum(cand.astype(jnp.int32))
            (idx,) = jnp.nonzero(cand, size=K, fill_value=0)     # [K] ascending
            valid = jax.lax.iota(jnp.int32, K) < n_cand
            cls2_t = jnp.take(cls_t, idx, axis=1)                # [L_p, K]
            lens2 = jnp.where(valid, jnp.take(lens, idx), 0)
            m2p = f2(cls2_t, lens2) & (valid[:, None] * jnp.uint8(0xFF))
            # caller rows for ALL candidate slots (K-domain, B = invalid;
            # invalid slots carry no m2p bits, so they can never surface
            # through the (row, rule) pair extraction)
            idx_caller_k = jnp.where(
                valid, jnp.take(order, idx), jnp.int32(B)
            )
            ab_caller = None
            if n_always:
                sel = (acc1[a_word, :] & a_mask[:, None]) != 0   # [n_abr, B]
                ab = jnp.zeros((n_always, acc1.shape[1]), dtype=jnp.uint8)
                ab = ab.at[a_rule].max(sel.astype(jnp.uint8))
                ab_caller = jnp.zeros_like(ab.T).at[order].set(ab.T)
            return {
                "lens_raw": lens_raw, "n_cand": n_cand, "m2p": m2p,
                "idx_caller_k": idx_caller_k, "ab_caller": ab_caller,
            }

        return core

    def _fused(self, B: int, L_p: int):
        key = (B, L_p)
        hit = self._fns.get(key)
        if hit is not None:
            return hit
        block, K = self.capacities(B)
        core = self._match_core(B, L_p, K, block)
        n_always = self.plan.n_always
        shifts = jnp.asarray([0, 8, 16, 24], dtype=jnp.int32)
        P = self.pair_capacity(B, K)

        @jax.jit
        def fused(cls_and_lens):
            """One int32 input transfer (the tunnel charges fixed latency
            per transfer, and int32 2-D is its fast path — see
            _match_core for the input layout) → one uint8 buffer:
              n_cand[4] ‖ n_pairs[4] ‖ (row, rule) pairs [4P] ‖
              always-rule bits [B * na8].
            A single buffer = a single device→host pull, and a SMALL one:
            each set rule bit ships as one int32 (pairs_from_core) instead
            of a full ceil(R/8)-byte row bitmap per matched line. At the
            tunnel's ~20-25 MB/s d2h the old row encoding (B/4 rows x
            125 B at 1k rules) cost ~80 ms per 64k batch — more than the
            kernels; pairs are ~30x smaller, so the pull is pure fixed
            latency (~65 ms) and pipelines away behind compute (see
            submit/collect). Stage-1's factor gate still bounds stage-2
            work to K candidate lines."""
            c = core(cls_and_lens)
            pairs, n_pairs, _ = self.pairs_from_core(c, K, P)
            parts = [
                ((c["n_cand"][None] >> shifts) & 0xFF).astype(jnp.uint8),
                ((n_pairs[None] >> shifts) & 0xFF).astype(jnp.uint8),
                ((pairs[:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1),
            ]
            if n_always:
                parts.append(
                    jnp.packbits(
                        c["ab_caller"].astype(jnp.bool_), axis=1
                    ).reshape(-1)
                )
            return jnp.concatenate(parts)

        self._fns[key] = (fused, K, P)
        return fused, K, P

    # ---- host API ----

    def submit(self, cls_ids: np.ndarray, lens: np.ndarray) -> _Pending:
        """Dispatch one batch; returns a handle whose device→host copy is
        already in flight. Pipelining batches through submit/collect hides
        the tunnel's fixed d2h latency behind the next batch's compute.

        Host cost is one combined-array assembly (a row-slice copy; no
        gather, no transpose — those run on device). With byte-size class
        partitions the class row packs 4 ids per int32: 4x less h2d volume
        AND a 4x smaller host copy."""
        cls_ids = np.asarray(cls_ids, dtype=np.int32)
        lens = np.asarray(lens, dtype=np.int32)
        B = cls_ids.shape[0]
        combined, Bp, L_p = self._assemble(cls_ids, lens)
        fn, K, P = self._fused(Bp, L_p)
        buf = fn(jnp.asarray(combined))
        try:
            buf.copy_to_host_async()
        except AttributeError:  # interpret/CPU arrays may lack the method
            pass
        return _Pending(
            buf=buf, B=B, K=K, P=P, lens=lens, h2d_bytes=combined.nbytes
        )

    def collect(self, p: _Pending) -> np.ndarray:
        """Block on a submit()ed batch → [B, n_rules] uint8 bits in caller
        row order. Raises PrefilterOverflow when either compaction capacity
        was exceeded (the caller reruns the batch single-stage)."""
        plan = self.plan
        buf = np.asarray(p.buf)
        p.d2h_bytes += buf.nbytes
        K, P, B = p.K, p.P, p.B
        R8 = self._nf8 * 8
        head = np.frombuffer(buf[:8].tobytes(), dtype="<i4")
        n_cand, n_pairs = int(head[0]), int(head[1])
        # observability: the stage-1 gate rate (≥ the true match rate; the
        # gap is the superimposition + factor false-positive cost that
        # stage 2 pays for). bench reports it as prefilter_gate_fraction.
        self.last_n_cand = n_cand
        if n_cand > K:
            raise PrefilterOverflow(f"{n_cand} candidates > capacity {K}")
        if n_pairs > P:
            raise PrefilterOverflow(f"{n_pairs} match pairs > capacity {P}")
        pairs = np.frombuffer(buf[8 : 8 + 4 * P].tobytes(), dtype="<i4")
        bits = np.zeros((B, plan.n_rules), dtype=np.uint8)
        if n_pairs:
            live = pairs[:n_pairs]
            rows_idx, cols = live // R8, live % R8
            keep = (
                (rows_idx >= 0) & (rows_idx < B)
                & (cols < plan.stage2.n_rules)
            )
            bits[rows_idx[keep], plan.f_idx[cols[keep]]] = 1
        if plan.n_always:
            off = 8 + 4 * P
            ap = buf[off:].reshape(-1, self._na8)[:B]  # caller-order rows
            abits = np.unpackbits(ap, axis=1, count=plan.n_always)
            abits[:, self._a_always] = 1
            if self._a_empty.any():
                abits[p.lens == 0] |= self._a_empty.astype(np.uint8)
            bits[:, plan.a_idx] = abits
        return bits

    def match_bits_encoded(
        self, cls_ids: np.ndarray, lens: np.ndarray
    ) -> np.ndarray:
        """[B, L] shared-class ids → [B, n_rules] uint8 device-decided bits.

        Same output contract as PrefilterMatcher.match_bits's first value
        (unsupported-rule columns all zero); raises PrefilterOverflow when
        the candidate capacity is exceeded. Sorts by length internally
        (pays off in both stages' tile-skip) and restores caller order.
        """
        if np.asarray(cls_ids).shape[0] == 0:
            return np.zeros((0, self.plan.n_rules), dtype=np.uint8)
        return self.collect(self.submit(cls_ids, lens))
