"""Two-stage literal-prefiltered matching (Hyperscan's decomposition, TPU-shaped).

The single-stage matcher scans every byte of every line against the full
ruleset NFA — cost ∝ total NFA width, even though almost all traffic matches
nothing. Production literal matchers (Hyperscan FDR/Teddy) exploit that: a
cheap literal scan gates the expensive automaton. This module is that
architecture built from the pieces this repo already has:

  stage 1 (every line): one packed NFA containing (a) the rules that have no
    required literal factor — they must always run — and (b) one *factor
    automaton* per distinct required literal (rulec.required_factors: a run
    of narrow byte classes every match of the branch must contain). This NFA
    is ~10x narrower than the full ruleset's, so the scan is ~10x cheaper.
  stage 2 (candidate lines only): the full NFA of the filterable rules, run
    only on lines where at least one factor hit. Benign traffic rarely
    contains attack-rule literals, so stage 2 typically sees a few percent
    of lines.

Soundness: factor absent ⟹ branch cannot match (rulec.required_factors),
so gating on "any factor hit" never drops a true match — the combined
bitmap is bit-identical to the single-stage matcher's, which the
differential tests assert.

Both stages reuse the same Pallas kernel / XLA scan and the same packing
(rulec.pack_programs); the prefilter is a compile-time rearrangement of the
ruleset, not new device code.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from banjax_tpu.matcher import nfa_jax
from banjax_tpu.matcher.encode import classify_bytes, encode_lines
from banjax_tpu.matcher.kernels import nfa_match
from banjax_tpu.matcher.rulec import (
    CompiledRules,
    RuleProgram,
    UnsupportedPattern,
    compile_rule,
    factor_program,
    pack_programs,
    required_factors,
)

log = logging.getLogger(__name__)

_MIN_BUCKET = 64


@dataclasses.dataclass
class PrefilterPlan:
    """Compile-time split of a ruleset into the two stage automata."""

    n_rules: int
    stage1: CompiledRules        # always-rules ++ literal factor automata
    n_always: int                # first n_always stage-1 columns are rules...
    a_idx: np.ndarray            # ...these original rule ids
    n_factors: int               # remaining stage-1 columns are factors
    stage2: CompiledRules        # filterable rules
    f_idx: np.ndarray            # stage-2 column -> original rule id
    unsupported: Dict[int, str]  # rule id -> reason (host regex fallback)


def build_plan(
    patterns: Sequence[str],
    min_factor_len: int = 3,
    max_factor_len: int = 12,
    min_filterable_fraction: float = 0.5,
) -> Optional[PrefilterPlan]:
    """Split `patterns` into the two-stage plan, or None when the ruleset
    doesn't profit (too few filterable rules — the two-pass overhead would
    outweigh the narrower stage 1)."""
    programs: List[Optional[RuleProgram]] = []
    unsupported: Dict[int, str] = {}
    for i, pat in enumerate(patterns):
        try:
            programs.append(compile_rule(pat))
        except UnsupportedPattern as e:
            programs.append(None)
            unsupported[i] = str(e)

    factor_key_to_col: Dict[Tuple, int] = {}
    factor_progs: List[RuleProgram] = []
    always_ids: List[int] = []
    filt_ids: List[int] = []
    for i, prog in enumerate(programs):
        if prog is None:
            continue  # host regex fallback, not on device at all
        factors = required_factors(
            prog, min_len=min_factor_len, max_len=max_factor_len
        )
        if factors is None:
            always_ids.append(i)
            continue
        filt_ids.append(i)
        for f in factors:
            key = tuple(p.cs for p in f)
            if key not in factor_key_to_col:
                factor_key_to_col[key] = len(factor_progs)
                factor_progs.append(factor_program(f))

    n_device = len(always_ids) + len(filt_ids)
    if (
        n_device == 0
        or not factor_progs
        or len(filt_ids) < n_device * min_filterable_fraction
    ):
        return None

    stage1_programs = [programs[i] for i in always_ids] + factor_progs
    stage2_programs = [programs[i] for i in filt_ids]
    s1 = pack_programs(stage1_programs, n_shards="auto")
    s2 = pack_programs(stage2_programs, n_shards="auto")
    log.info(
        "prefilter plan: %d always + %d filterable rules, %d distinct factors; "
        "stage1 %d words, stage2 %d words",
        len(always_ids), len(filt_ids), len(factor_progs),
        s1.n_words, s2.n_words,
    )
    return PrefilterPlan(
        n_rules=len(patterns),
        stage1=s1,
        n_always=len(always_ids),
        a_idx=np.asarray(always_ids, dtype=np.int64),
        n_factors=len(factor_progs),
        stage2=s2,
        f_idx=np.asarray(filt_ids, dtype=np.int64),
        unsupported=unsupported,
    )


class PrefilterMatcher:
    """Executable two-stage pipeline over a PrefilterPlan.

    backend: "pallas" | "pallas-interpret" | "xla" — same meanings as the
    runner's matcher_backend resolution.
    """

    def __init__(self, plan: PrefilterPlan, backend: str, max_len: int,
                 max_batch: int = 16384):
        self.plan = plan
        self.max_len = max_len
        self.max_batch = max(_MIN_BUCKET, max_batch)
        self.backend = backend
        self.interpret = backend == "pallas-interpret"
        self._preps = {}
        if backend in ("pallas", "pallas-interpret"):
            self._preps = {
                "s1": nfa_match.prepare(plan.stage1),
                "s2": nfa_match.prepare(plan.stage2),
            }
        else:
            self._params = {
                "s1": nfa_jax.match_params(plan.stage1),
                "s2": nfa_jax.match_params(plan.stage2),
            }

    def _run_stage(self, which: str, compiled: CompiledRules,
                   cls_ids: np.ndarray, lens: np.ndarray) -> np.ndarray:
        """[N, n_cols] uint8 match bits for one stage, bucketed/padded."""
        n = len(lens)
        out = np.zeros((n, compiled.n_rules), dtype=np.uint8)
        for start in range(0, n, self.max_batch):
            stop = min(n, start + self.max_batch)
            b = _bucket(stop - start, self.max_batch)
            pad_cls = np.zeros((b, cls_ids.shape[1]), dtype=np.int32)
            pad_len = np.zeros(b, dtype=np.int32)
            pad_cls[: stop - start] = cls_ids[start:stop]
            pad_len[: stop - start] = lens[start:stop]
            if self._preps:
                packed = nfa_match.match_batch_pallas(
                    self._preps[which], pad_cls, pad_len,
                    interpret=self.interpret, packed=True,
                )
            else:
                import jax.numpy as jnp  # local: keep module import light

                packed = np.asarray(
                    nfa_jax.match_batch_packed(
                        self._params[which], jnp.asarray(pad_cls),
                        jnp.asarray(pad_len), compiled.n_rules,
                    )
                )
            out[start:stop] = np.unpackbits(
                packed, axis=1, count=compiled.n_rules
            )[: stop - start]
        return out

    def match_bits(
        self, rests: Sequence[str]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """([N, n_rules] uint8 device-decided bits, [N] bool host_eval).

        host_eval rows (non-ASCII / over-long) carry all-zero bits; rules in
        plan.unsupported carry all-zero columns — the caller routes both to
        its host regex fallback exactly as for the single-stage matcher.
        """
        plan = self.plan
        bits = np.zeros((len(rests), plan.n_rules), dtype=np.uint8)

        bytes_mat, lens, host_eval = encode_lines(rests, self.max_len)
        rows = np.flatnonzero(~host_eval)
        if rows.size == 0:
            return bits, host_eval
        cls1 = classify_bytes(plan.stage1, bytes_mat[rows], lens[rows])
        s1 = self._run_stage("s1", plan.stage1, cls1, lens[rows])
        if plan.n_always:
            bits[np.ix_(rows, plan.a_idx)] = s1[:, : plan.n_always]

        cand_local = np.flatnonzero(s1[:, plan.n_always :].any(axis=1))
        if cand_local.size:
            cand_rows = rows[cand_local]
            cls2 = classify_bytes(
                plan.stage2, bytes_mat[cand_rows], lens[cand_rows]
            )
            s2 = self._run_stage("s2", plan.stage2, cls2, lens[cand_rows])
            bits[np.ix_(cand_rows, plan.f_idx)] = s2
        return bits, host_eval


def _bucket(n: int, cap: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return min(b, max(cap, _MIN_BUCKET))
