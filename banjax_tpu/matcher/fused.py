"""Fused UA + path matching on device (SURVEY.md C7 TPU plan;
BASELINE.json configs[3]).

The reference checks User-Agent patterns serially per request in severity
order (/root/reference/internal/user_agent_decision.go:55-64) and path/rate
rules serially per log line (regex_rate_limiter.go:216-269). On TPU both
 ruleset kinds compile into ONE batched NFA: UA patterns (regexes as-is,
substring patterns as escaped literals) occupy columns after the rate
rules, so a single kernel pass over a line batch yields both the rate-rule
bitmap and the UA bitmap — `DeviceUAMatcher` then reduces the UA columns to
the reference's first-match-in-severity-order decision.

Substring-vs-regex auto-detection follows ua_lists.contains_regex_metachar
exactly, so device results are differentially testable against
check_ua_decision (tests/unit/test_fused.py).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from banjax_tpu.decisions.model import Decision
from banjax_tpu.decisions.ua_lists import _UA_CHECK_ORDER, UARules
from banjax_tpu.matcher import nfa_jax
from banjax_tpu.matcher.encode import encode_for_match
from banjax_tpu.matcher.kernels import nfa_match as pallas_nfa
from banjax_tpu.matcher.rulec import compile_rules


def ua_patterns_in_severity_order(rules: UARules) -> List[Tuple[Decision, str]]:
    """Flatten a UARules map into (decision, regex_string) rows in the exact
    order check_ua_decision scans them; substring patterns are escaped."""
    out: List[Tuple[Decision, str]] = []
    for d in _UA_CHECK_ORDER:
        for p in rules.get(d, ()):
            out.append((d, p.raw if p.compiled is not None else re.escape(p.raw)))
    return out


class DeviceUAMatcher:
    """Batched UA classification: one NFA pass, then severity-order argmax."""

    def __init__(self, rules: UARules, max_len: int = 256,
                 backend: str = "xla", extra_rules: Sequence[str] = ()):
        """`extra_rules` are regex strings (e.g. the rate rules) fused into
        the same compiled ruleset; their match bits come back separately
        from match_bits()."""
        self._rows = ua_patterns_in_severity_order(rules)
        self.n_extra = len(extra_rules)
        patterns = list(extra_rules) + [rx for _, rx in self._rows]
        self.compiled = compile_rules(patterns, n_shards="auto")
        self._decisions = [d for d, _ in self._rows]
        self.max_len = max_len
        self.backend = backend
        self._params = None
        self._prep = None
        if backend in ("pallas", "pallas-interpret"):
            self._prep = pallas_nfa.prepare(self.compiled)
        else:
            self._params = nfa_jax.match_params(self.compiled)
        # host fallback for rules the compiler can't lower or non-ASCII lines
        self._host_rx = [re.compile(p) for p in patterns]
        self._host_rule_idx = [
            i for i in range(len(patterns)) if not self.compiled.device_ok[i]
        ]

    def match_bits(self, lines: Sequence[str]) -> np.ndarray:
        """[B, n_extra + n_ua_patterns] uint8 — the fused bitmap."""
        cls_ids, lens, host_eval = encode_for_match(
            self.compiled, lines, self.max_len
        )
        n = len(lines)
        bits = np.zeros((n, self.compiled.n_rules), dtype=np.uint8)
        rows = np.flatnonzero(~host_eval)
        if rows.size:
            if self._prep is not None:
                bits[rows] = pallas_nfa.match_batch_pallas(
                    self._prep, cls_ids[rows], lens[rows],
                    interpret=self.backend == "pallas-interpret",
                )
            else:
                bits[rows] = np.asarray(
                    nfa_jax.match_batch(
                        self._params, cls_ids[rows], lens[rows],
                        self.compiled.n_rules,
                    )
                )
        for row in np.flatnonzero(host_eval):
            for i, rx in enumerate(self._host_rx):
                if rx.search(lines[row]) is not None:
                    bits[row, i] = 1
        for i in self._host_rule_idx:
            rx = self._host_rx[i]
            for row in rows:
                if rx.search(lines[row]) is not None:
                    bits[row, i] = 1
        return bits

    def decide(self, ua_bits: np.ndarray) -> List[Tuple[Optional[Decision], bool]]:
        """Reduce UA columns (bitmap WITHOUT the extra-rule columns) to the
        reference's first-match-in-severity-order result per row."""
        out: List[Tuple[Optional[Decision], bool]] = []
        for row in ua_bits:
            hit = np.flatnonzero(row)
            if hit.size:
                out.append((self._decisions[int(hit[0])], True))
            else:
                out.append((None, False))
        return out

    def check_batch(self, user_agents: Sequence[str]) -> List[Tuple[Optional[Decision], bool]]:
        """Batched check_ua_decision (identical results, one device pass)."""
        bits = self.match_bits(user_agents)
        return self.decide(bits[:, self.n_extra :])
