"""Batched bit-parallel NFA matching on device.

This is the device half of the TPU matcher: the packed transition tensors
from banjax_tpu/matcher/rulec.py are evaluated for a whole batch of encoded
log lines in one `lax.scan` over byte columns. It replaces the serial
per-(line, rule) regexp.Match hot loop of the reference
(/root/reference/internal/regex_rate_limiter.go:234) with O(L) vectorized
steps over a [batch, words] uint32 state array — all lines × all rules at
once, XLA-fusable, and shardable on both the line axis (data parallel) and
the word axis (rule parallel; branches never straddle shard boundaries by
construction, see rulec.CompiledRules).

Semantics per step (bit p = "positions 1..p of p's branch match a suffix
ending at the current byte"):

    D' = (((D << 1) | inject) & B[class]) | (D & B[class] & selfloop)

`inject` restarts every branch at every byte (unanchored search semantics);
`^`-anchored branches inject only at byte 0. Accept bits accumulate every
step (`accept_any`) or only on each line's final byte (`accept_end`, the
`$` anchor). Pad bytes are encoded as class 0, whose b_table row is all
zeros, so state collapses to 0 past end-of-line without explicit masking.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp

from banjax_tpu.matcher.rulec import CompiledRules


def match_params(compiled: CompiledRules) -> Dict[str, jnp.ndarray]:
    """Device-resident parameter pytree for match_batch."""
    return {
        "b_table": jnp.asarray(compiled.b_table),
        "shift_in": jnp.asarray(compiled.shift_in),
        "inject_always": jnp.asarray(compiled.inject_always),
        "inject_start": jnp.asarray(compiled.inject_start),
        "selfloop": jnp.asarray(compiled.selfloop),
        "accept_any": jnp.asarray(compiled.accept_any),
        "accept_end": jnp.asarray(compiled.accept_end),
        "acc_word": jnp.asarray(compiled.acc_word),
        "acc_mask": jnp.asarray(compiled.acc_mask),
        "branch_rule": jnp.asarray(compiled.branch_rule),
        "always_match": jnp.asarray(compiled.always_match),
        "empty_only": jnp.asarray(compiled.empty_only),
    }


def nfa_scan(
    params: Dict[str, jnp.ndarray],
    cls_ids: jnp.ndarray,  # [B, L] int32 byte-class ids (0 = pad)
    lens: jnp.ndarray,     # [B] int32 true line lengths
) -> jnp.ndarray:
    """Run the shift-and scan; returns accumulated accept words [B, W] uint32."""
    B, L = cls_ids.shape
    W = params["b_table"].shape[1]
    zero = jnp.uint32(0)
    d0 = jnp.zeros((B, W), dtype=jnp.uint32)
    acc0 = jnp.zeros((B, W), dtype=jnp.uint32)

    shift_in = params["shift_in"]
    inject_always = params["inject_always"]
    inject_start = params["inject_start"]
    selfloop = params["selfloop"]
    accept_any = params["accept_any"]
    accept_end = params["accept_end"]
    b_table = params["b_table"]
    last_col = (lens - 1)[:, None]  # [B, 1]

    def step(carry, xs):
        d, acc = carry
        cls_col, l = xs  # [B], scalar
        bmask = jnp.take(b_table, cls_col, axis=0)  # [B, W]
        carry_bits = jnp.concatenate(
            [jnp.zeros((B, 1), dtype=jnp.uint32), d[:, :-1] >> 31], axis=1
        )
        shifted = ((d << 1) | carry_bits) & shift_in
        inject = inject_always | jnp.where(l == 0, inject_start, zero)
        new_d = ((shifted | inject) & bmask) | (d & bmask & selfloop)
        acc = acc | (new_d & accept_any)
        at_end = l == last_col  # [B, 1]
        acc = acc | jnp.where(at_end, new_d & accept_end, zero)
        return (new_d, acc), None

    (_, acc), _ = jax.lax.scan(
        step, (d0, acc0), (cls_ids.T, jnp.arange(L, dtype=jnp.int32))
    )
    return acc


def extract_matches(
    params: Dict[str, jnp.ndarray],
    acc: jnp.ndarray,   # [B, W] accumulated accept words
    lens: jnp.ndarray,  # [B]
    n_rules: int,
) -> jnp.ndarray:
    """Reduce accept words to per-rule match bits [B, n_rules] (uint8 0/1)."""
    B = acc.shape[0]
    matched = jnp.zeros((B, n_rules), dtype=jnp.uint8)
    if params["acc_word"].shape[0] > 0:
        sel = (acc[:, params["acc_word"]] & params["acc_mask"]) != 0  # [B, n_br]
        matched = matched.at[:, params["branch_rule"]].max(sel.astype(jnp.uint8))
    matched = matched | params["always_match"].astype(jnp.uint8)[None, :]
    empty = (lens == 0)[:, None]
    matched = matched | (params["empty_only"].astype(jnp.uint8)[None, :] & empty.astype(jnp.uint8))
    return matched


@functools.partial(jax.jit, static_argnames=("n_rules",))
def match_batch(
    params: Dict[str, jnp.ndarray],
    cls_ids: jnp.ndarray,
    lens: jnp.ndarray,
    n_rules: int,
) -> jnp.ndarray:
    """[B, L] encoded lines → [B, n_rules] uint8 match bits."""
    acc = nfa_scan(params, cls_ids, lens)
    return extract_matches(params, acc, lens, n_rules)


@functools.partial(jax.jit, static_argnames=("n_rules",))
def match_batch_packed(
    params: Dict[str, jnp.ndarray],
    cls_ids: jnp.ndarray,
    lens: jnp.ndarray,
    n_rules: int,
) -> jnp.ndarray:
    """match_batch with the rule axis bit-packed on device ([B, ceil(R/8)]
    uint8) — 8× less device→host traffic for the runner's bitmap pull."""
    acc = nfa_scan(params, cls_ids, lens)
    matched = extract_matches(params, acc, lens, n_rules)
    return jnp.packbits(matched.astype(jnp.bool_), axis=1)


# host-side line encoding lives in banjax_tpu/matcher/encode.py
