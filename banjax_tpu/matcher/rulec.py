"""Rule compiler: RE2-subset regexes → packed bit-parallel NFA tensors.

The reference compiles each rate-limit rule with Go's regexp (RE2) at config
load time (/root/reference/internal/config.go:96-131) and then runs one
regexp.Match per (line, rule) in the tailer hot loop
(/root/reference/internal/regex_rate_limiter.go:234). This module is the
TPU-first replacement for that hot loop's *compile* side: every rule is
lowered to a Glushkov-style position automaton and all rules are packed
together into a handful of small integer tensors that a single batched
shift-and pass (banjax_tpu/matcher/nfa_jax.py) evaluates for thousands of
lines at once.

Lowering pipeline
-----------------
1. Parse the pattern (RE2 subset: literals, escapes, classes, `.`, anchors,
   groups, alternation, `? * + {m,n}` quantifiers, `(?i)`/`(?s)` flags) into
   an AST.
2. Expand the AST into a set of **branches**: each branch is a concatenation
   of *positions*, where a position is a byte-class plus an optional
   self-loop (self-loops encode `C+`; `C*`/`C?`/`{m,n}` expand into multiple
   branches). `^`/`$` become per-branch anchor flags. Expansion is capped;
   rules that exceed the caps or use constructs with no finite branch form
   (unbounded group repeats, `\b`, `(?m)`, non-ASCII literals) raise
   UnsupportedPattern and fall back per-rule to the host `re` path, exactly
   as SURVEY.md §7.1 prescribes.
3. Assign every position a bit in a packed uint32 word array (branches never
   straddle shard boundaries, so the match kernel can shard the word axis
   across devices), compute global byte equivalence classes over all rule
   charsets, and emit the transition masks.

Match-time semantics (implemented by nfa_jax.match_batch): bit p of state D
is set after consuming byte c iff positions 1..p of p's branch match a
suffix of the input ending at c.  One step is

    D' = (((D << 1) | inject) & B[class(c)]) | (D & B[class(c)] & selfloop)

with the packed shift carrying bit 31 → bit 0 of the next word, masked by
`shift_in` so carries never leak across branch starts.  A rule matches when
any of its branches' accept bits is ever set (`accept_any`), or is set on
the final byte for `$`-anchored branches (`accept_end`).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

INF = -1  # open upper bound for repeats

# Expansion caps: a rule exceeding these falls back to the host regex path.
MAX_BRANCHES_PER_RULE = 256
MAX_POSITIONS_PER_RULE = 1024
MAX_GROUP_REPEAT = 16


class UnsupportedPattern(ValueError):
    """Pattern is valid RE2 but has no finite branch form on the device path."""


# ---------------------------------------------------------------------------
# byte sets as 256-bit Python ints (bit b set ⟺ byte b in the set)
# ---------------------------------------------------------------------------

ALL_BYTES = (1 << 256) - 1


def _bit(b: int) -> int:
    return 1 << b


def _range(lo: int, hi: int) -> int:
    return ((1 << (hi + 1)) - 1) ^ ((1 << lo) - 1)


def _from_chars(chars: str) -> int:
    mask = 0
    for ch in chars:
        mask |= _bit(ord(ch))
    return mask


# Python-`re`-on-str semantics restricted to ASCII (the oracle the TPU path
# is differential-tested against is CpuMatcher, which uses Python re; lines
# containing non-ASCII bytes are routed to the host path by the encoder).
DIGIT = _range(0x30, 0x39)
WORD = DIGIT | _range(0x41, 0x5A) | _range(0x61, 0x7A) | _bit(0x5F)
# Python-re \s over ASCII: space, \t\n\r\f\v plus the FS/GS/RS/US controls
# (0x1C-0x1F); \x85/\xa0 are non-ASCII and host-routed by the encoder
SPACE = _from_chars(" \t\n\r\f\v") | _range(0x1C, 0x1F)
DOT_NO_NL = ALL_BYTES & ~_bit(0x0A)

_POSIX_CLASSES = {
    "alnum": DIGIT | _range(0x41, 0x5A) | _range(0x61, 0x7A),
    "alpha": _range(0x41, 0x5A) | _range(0x61, 0x7A),
    "ascii": _range(0x00, 0x7F),
    "blank": _from_chars(" \t"),
    "cntrl": _range(0x00, 0x1F) | _bit(0x7F),
    "digit": DIGIT,
    "graph": _range(0x21, 0x7E),
    "lower": _range(0x61, 0x7A),
    "print": _range(0x20, 0x7E),
    "punct": _range(0x21, 0x2F) | _range(0x3A, 0x40) | _range(0x5B, 0x60) | _range(0x7B, 0x7E),
    "space": SPACE,
    "upper": _range(0x41, 0x5A),
    "word": WORD,
    "xdigit": DIGIT | _range(0x41, 0x46) | _range(0x61, 0x66),
}

_SIMPLE_ESCAPES = {
    "n": _bit(0x0A), "t": _bit(0x09), "r": _bit(0x0D),
    "f": _bit(0x0C), "v": _bit(0x0B), "a": _bit(0x07),
    "d": DIGIT, "D": ALL_BYTES & ~DIGIT,
    "w": WORD, "W": ALL_BYTES & ~WORD,
    "s": SPACE, "S": ALL_BYTES & ~SPACE,
}


def _fold_case(mask: int) -> int:
    """ASCII case folding for (?i)."""
    out = mask
    for b in range(0x41, 0x5B):  # A-Z
        if mask & _bit(b):
            out |= _bit(b + 0x20)
    for b in range(0x61, 0x7B):  # a-z
        if mask & _bit(b):
            out |= _bit(b - 0x20)
    return out


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------
# nodes: ("empty",) | ("cs", mask) | ("cat", [..]) | ("alt", [..])
#        | ("rep", node, m, n) | ("^",) | ("$",)

FLAG_I = 1  # case-insensitive
FLAG_S = 2  # dot matches newline
FLAG_M = 4  # multiline (unsupported on device)


class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def error(self, msg: str) -> UnsupportedPattern:
        return UnsupportedPattern(f"{msg} at index {self.i} in {self.p!r}")

    def eof(self) -> bool:
        return self.i >= len(self.p)

    def peek(self) -> str:
        return self.p[self.i] if self.i < len(self.p) else ""

    def next(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    def parse(self) -> tuple:
        node = self._alt(0)
        if not self.eof():
            raise self.error(f"unexpected {self.peek()!r}")
        return node

    # alternation scope; `flags` may be updated mid-scope by (?i)-style
    # directives, which in RE2 apply to the rest of the enclosing group
    def _alt(self, flags: int) -> tuple:
        box = [flags]
        parts = [self._cat(box)]
        while not self.eof() and self.peek() == "|":
            self.next()
            parts.append(self._cat(box))
        return parts[0] if len(parts) == 1 else ("alt", parts)

    def _cat(self, flagbox: List[int]) -> tuple:
        items: List[tuple] = []
        while not self.eof() and self.peek() not in "|)":
            atom = self._atom(flagbox)
            if atom is None:  # inline flag directive, already applied
                continue
            items.append(self._quantified(atom, flagbox))
        if not items:
            return ("empty",)
        return items[0] if len(items) == 1 else ("cat", items)

    def _quantified(self, atom: tuple, flagbox: List[int]) -> tuple:
        while not self.eof() and self.peek() in "*+?{":
            if self.peek() == "{":
                rep = self._try_counted_repeat()
                if rep is None:  # literal '{'
                    break
                m, n = rep
            else:
                ch = self.next()
                m, n = {"*": (0, INF), "+": (1, INF), "?": (0, 1)}[ch]
            if atom[0] in ("^", "$"):
                raise self.error("quantifier on anchor")
            if not self.eof() and self.peek() == "?":
                self.next()  # lazy quantifier: same language, drop
            # rep-of-rep only arises via groups, e.g. (a?){2} — bare double
            # quantifiers (a**) were already rejected by the Python re
            # compile at config load (schema.RegexWithRate.from_yaml_dict)
            atom = ("rep", atom, m, n)
        return atom

    def _try_counted_repeat(self) -> Optional[Tuple[int, int]]:
        start = self.i
        self.next()  # '{'
        digits = ""
        while not self.eof() and self.peek().isdigit():
            digits += self.next()
        if not digits:
            self.i = start
            return None
        m = int(digits)
        if self.eof():
            self.i = start
            return None
        ch = self.next()
        if ch == "}":
            return m, m
        if ch != ",":
            self.i = start
            return None
        digits2 = ""
        while not self.eof() and self.peek().isdigit():
            digits2 += self.next()
        if self.eof() or self.next() != "}":
            self.i = start
            return None
        if digits2 == "":
            return m, INF
        n = int(digits2)
        if n < m:
            raise self.error("bad repeat bounds")
        return m, n

    def _atom(self, flagbox: List[int]) -> Optional[tuple]:
        flags = flagbox[0]
        ch = self.next()
        if ch == "(":
            return self._group(flagbox)
        if ch == "[":
            return ("cs", self._char_class(flags))
        if ch == ".":
            return ("cs", ALL_BYTES if flags & FLAG_S else DOT_NO_NL)
        if ch == "^":
            if flags & FLAG_M:
                raise self.error("multiline ^ not supported on device")
            return ("^",)
        if ch == "$":
            if flags & FLAG_M:
                raise self.error("multiline $ not supported on device")
            return ("$",)
        if ch == "\\":
            return self._escape(flags)
        if ch in "*+?":
            raise self.error("quantifier with nothing to repeat")
        code = ord(ch)
        if code > 0x7F:
            raise UnsupportedPattern(f"non-ASCII literal {ch!r} in {self.p!r}")
        mask = _bit(code)
        return ("cs", _fold_case(mask) if flags & FLAG_I else mask)

    def _group(self, flagbox: List[int]) -> Optional[tuple]:
        flags = flagbox[0]
        if self.peek() == "?":
            self.next()
            if self.peek() == ":":
                self.next()
                node = self._alt(flags)
            elif self.peek() == "P":
                self.next()
                if self.peek() != "<":
                    raise self.error("unsupported (?P...) form")
                self.next()
                while not self.eof() and self.peek() != ">":
                    self.next()
                if self.eof():
                    raise self.error("unterminated group name")
                self.next()
                node = self._alt(flags)
            elif self.peek() in "imsUx-":
                new_flags, scoped = self._flag_directive(flags)
                if scoped is None:
                    # (?i) — applies to the rest of the group; consume the ')'
                    flagbox[0] = new_flags
                    if self.eof() or self.next() != ")":
                        raise self.error("missing )")
                    return None
                node = scoped
            else:
                raise self.error(f"unsupported group (?{self.peek()}")
        else:
            node = self._alt(flags)
        if self.eof() or self.next() != ")":
            raise self.error("missing )")
        return node

    def _flag_directive(self, flags: int) -> Tuple[int, Optional[tuple]]:
        """(?flags) or (?flags:...) or (?flags-flags...)."""
        negate = False
        while True:
            ch = self.peek()
            if ch == "i":
                flags = (flags & ~FLAG_I) if negate else (flags | FLAG_I)
            elif ch == "s":
                flags = (flags & ~FLAG_S) if negate else (flags | FLAG_S)
            elif ch == "m":
                if not negate:
                    raise UnsupportedPattern("(?m) not supported on device")
                flags &= ~FLAG_M
            elif ch == "U":
                pass  # swap-greediness: same language
            elif ch == "x":
                raise UnsupportedPattern("(?x) free-spacing not supported")
            elif ch == "-":
                negate = True
            elif ch == ":":
                self.next()
                return flags, self._alt(flags)
            elif ch == ")":
                return flags, None
            else:
                raise self.error(f"bad flag {ch!r}")
            self.next()

    def _escape(self, flags: int) -> tuple:
        if self.eof():
            raise self.error("trailing backslash")
        ch = self.next()
        if ch == "A":
            return ("^",)
        if ch in "zZ":  # Go spells it \z, Python \Z; same end-of-text anchor
            return ("$",)
        if ch in "bB":
            raise UnsupportedPattern(f"\\{ch} word boundary not supported on device")
        if ch in "pP":
            raise UnsupportedPattern(f"\\{ch} unicode class not supported on device")
        if ch.isdigit() and ch != "0":
            raise UnsupportedPattern("backreference")  # re2check rejects earlier
        mask = self._escape_mask(ch, flags)
        return ("cs", mask)

    def _escape_mask(self, ch: str, flags: int) -> int:
        if ch in _SIMPLE_ESCAPES:
            mask = _SIMPLE_ESCAPES[ch]
            if flags & FLAG_I and ch in "wW":
                pass  # \w already case-closed
            return mask
        if ch == "x":
            if self.peek() == "{":
                self.next()
                digits = ""
                while not self.eof() and self.peek() != "}":
                    digits += self.next()
                if self.eof():
                    raise self.error("unterminated \\x{")
                self.next()
                code = int(digits, 16)
            else:
                digits = ""
                for _ in range(2):
                    if self.eof():
                        raise self.error("bad \\x escape")
                    digits += self.next()
                code = int(digits, 16)
            if code > 0xFF:
                raise UnsupportedPattern(f"\\x{{{code:x}}} beyond byte range")
            mask = _bit(code)
            return _fold_case(mask) if flags & FLAG_I else mask
        if ch == "0":
            return _bit(0)
        code = ord(ch)
        if code > 0x7F:
            raise UnsupportedPattern(f"non-ASCII escape {ch!r}")
        mask = _bit(code)
        if ch.isalpha():
            return _fold_case(mask) if flags & FLAG_I else mask
        return mask

    def _char_class(self, flags: int) -> int:
        negated = False
        if self.peek() == "^":
            self.next()
            negated = True
        mask = 0
        first = True
        while True:
            if self.eof():
                raise self.error("unterminated character class")
            ch = self.next()
            if ch == "]" and not first:
                break
            first = False
            if ch == "[" and self.peek() == ":":
                # POSIX class [:name:]
                j = self.p.find(":]", self.i)
                if j == -1:
                    raise self.error("unterminated POSIX class")
                name = self.p[self.i + 1 : j]
                neg = name.startswith("^")
                if neg:
                    name = name[1:]
                if name not in _POSIX_CLASSES:
                    raise self.error(f"unknown POSIX class {name!r}")
                m = _POSIX_CLASSES[name]
                mask |= (ALL_BYTES & ~m) if neg else m
                self.i = j + 2
                continue
            if ch == "\\":
                if self.eof():
                    raise self.error("trailing backslash in class")
                esc = self.next()
                if esc in "dDwWsS":
                    mask |= _SIMPLE_ESCAPES[esc]
                    continue
                lo = self._class_single_escape(esc)
            else:
                code = ord(ch)
                if code > 0x7F:
                    raise UnsupportedPattern(f"non-ASCII {ch!r} in class")
                lo = code
            # range?
            if self.peek() == "-" and self.i + 1 < len(self.p) and self.p[self.i + 1] != "]":
                self.next()  # '-'
                ch2 = self.next()
                if ch2 == "\\":
                    hi = self._class_single_escape(self.next())
                else:
                    code2 = ord(ch2)
                    if code2 > 0x7F:
                        raise UnsupportedPattern(f"non-ASCII {ch2!r} in class")
                    hi = code2
                if hi < lo:
                    raise self.error("reversed class range")
                mask |= _range(lo, hi)
            else:
                mask |= _bit(lo)
        if flags & FLAG_I:
            mask = _fold_case(mask)
        if negated:
            mask = ALL_BYTES & ~mask
        return mask

    def _class_single_escape(self, esc: str) -> int:
        single = {"n": 0x0A, "t": 0x09, "r": 0x0D, "f": 0x0C, "v": 0x0B,
                  "a": 0x07, "b": 0x08, "0": 0x00}
        if esc in single:
            return single[esc]
        if esc == "x":
            digits = ""
            if self.peek() == "{":
                self.next()
                while not self.eof() and self.peek() != "}":
                    digits += self.next()
                if self.eof():
                    raise self.error("unterminated \\x{ in class")
                self.next()
            else:
                for _ in range(2):
                    if self.eof():
                        raise self.error("bad \\x escape in class")
                    digits += self.next()
            code = int(digits, 16)
            if code > 0xFF:
                raise UnsupportedPattern("\\x beyond byte range in class")
            return code
        code = ord(esc)
        if code > 0x7F:
            raise UnsupportedPattern(f"non-ASCII escape {esc!r} in class")
        return code


# ---------------------------------------------------------------------------
# Lowering: AST → branches of positions
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Pos:
    cs: int          # 256-bit byte set
    loop: bool = False  # self-loop (the position absorbs 1+ repeats)


# branch sequence items: Pos | "^" | "$"
_Seq = Tuple[object, ...]


class _Caps:
    def __init__(self) -> None:
        self.branches = MAX_BRANCHES_PER_RULE
        self.positions = MAX_POSITIONS_PER_RULE

    def check(self, seqs: Sequence[_Seq]) -> Sequence[_Seq]:
        if len(seqs) > self.branches:
            raise UnsupportedPattern(
                f"rule expands to {len(seqs)} branches (cap {self.branches})"
            )
        total = sum(sum(1 for it in s if isinstance(it, Pos)) for s in seqs)
        if total > self.positions:
            raise UnsupportedPattern(
                f"rule expands to {total} positions (cap {self.positions})"
            )
        return seqs


def _lower(node: tuple, caps: _Caps) -> List[_Seq]:
    kind = node[0]
    if kind == "empty":
        return [()]
    if kind == "cs":
        return [(Pos(node[1]),)]
    if kind in ("^", "$"):
        return [(kind,)]
    if kind == "cat":
        seqs: List[_Seq] = [()]
        for child in node[1]:
            child_seqs = _lower(child, caps)
            seqs = caps.check([a + b for a in seqs for b in child_seqs])
        return seqs
    if kind == "alt":
        out: List[_Seq] = []
        for child in node[1]:
            out.extend(_lower(child, caps))
        return list(caps.check(out))
    if kind == "rep":
        return _lower_rep(node, caps)
    raise AssertionError(f"unknown node {kind}")


def _lower_rep(node: tuple, caps: _Caps) -> List[_Seq]:
    _, inner, m, n = node
    alts = _lower(inner, caps)
    if any("^" in a or "$" in a for a in alts):
        # anchors under a repeat: expand finitely below (anchored branches
        # are pruned/validated later); unbounded anchored repeats are dead
        # beyond one iteration, so treat X{m,INF} as X{m,m+1}
        if n == INF:
            n = max(m, 1)
        return _lower_rep_general(alts, m, n, caps)
    if () in alts:
        # (X|ε){m,n} ≡ X{0,n}
        alts = [a for a in alts if a != ()]
        m = 0
        if not alts:
            return [()]
    single = all(len(a) == 1 and isinstance(a[0], Pos) for a in alts)
    if single:
        loops = [a[0].loop for a in alts]
        union = 0
        for a in alts:
            union |= a[0].cs
        if n == INF:
            # (C1|..|Ck){m,∞} with single-byte alternatives ≡ [C∪]{m,∞}
            if m == 0:
                return [(), (Pos(union, loop=True),)]
            return [tuple([Pos(union)] * (m - 1) + [Pos(union, loop=True)])]
        if len(alts) == 1 and loops[0]:
            # (C+){m,n} ≡ C{m,∞} for n ≥ m ≥ 1; (C+){0,n} ≡ C*
            if m == 0:
                return [(), (Pos(union, loop=True),)]
            return [tuple([Pos(union)] * (m - 1) + [Pos(union, loop=True)])]
        if not any(loops):
            # exact finite expansion of a plain byte class
            return list(caps.check([tuple([Pos(union)] * k) for k in range(m, n + 1)]))
        # mixed looped/plain single-byte alternatives with finite n: general
    if n == INF:
        raise UnsupportedPattern("unbounded repeat of a multi-byte group")
    return _lower_rep_general(alts, m, n, caps)


def _lower_rep_general(alts: List[_Seq], m: int, n: int, caps: _Caps) -> List[_Seq]:
    if n > MAX_GROUP_REPEAT:
        raise UnsupportedPattern(f"group repeat bound {n} exceeds cap {MAX_GROUP_REPEAT}")
    out: List[_Seq] = []
    for k in range(m, n + 1):
        seqs: List[_Seq] = [()]
        for _ in range(k):
            seqs = caps.check([a + b for a in seqs for b in alts])
        out.extend(seqs)
    # dedupe identical branches
    seen = set()
    deduped = []
    for s in out:
        if s not in seen:
            seen.add(s)
            deduped.append(s)
    return list(caps.check(deduped))


@dataclasses.dataclass(frozen=True)
class Branch:
    positions: Tuple[Pos, ...]
    anchored_start: bool
    anchored_end: bool


@dataclasses.dataclass
class RuleProgram:
    """One rule lowered to branches (device form) or flagged degenerate."""

    branches: List[Branch]
    always_match: bool = False   # an unanchored-empty branch: matches everything
    empty_only: bool = False     # a `^$` branch: matches only empty input


def _finalize_branch(seq: _Seq) -> Optional[Branch]:
    """Resolve anchors; returns None for dead branches (e.g. `a^b`)."""
    anchored_start = anchored_end = False
    positions: List[Pos] = []
    for item in seq:
        if item == "^":
            if positions:
                return None  # ^ after consuming input: unmatchable
            anchored_start = True
        elif item == "$":
            anchored_end = True
        else:
            if anchored_end:
                return None  # input after $: unmatchable
            positions.append(item)  # type: ignore[arg-type]
    for p in positions:
        if p.cs == 0:
            return None  # empty byte class can never match
    return Branch(tuple(positions), anchored_start, anchored_end)


def compile_rule(pattern: str) -> RuleProgram:
    """Lower one RE2-subset pattern. Raises UnsupportedPattern on fallback."""
    ast = _Parser(pattern).parse()
    caps = _Caps()
    seqs = _lower(ast, caps)
    prog = RuleProgram(branches=[])
    seen = set()
    for seq in seqs:
        br = _finalize_branch(seq)
        if br is None:
            continue
        if not br.positions:
            if br.anchored_start and br.anchored_end:
                prog.empty_only = True
            else:
                # empty match exists in every input (search semantics)
                prog.always_match = True
            continue
        key = (br.positions, br.anchored_start, br.anchored_end)
        if key not in seen:
            seen.add(key)
            prog.branches.append(br)
    if prog.always_match:
        prog.branches = []  # everything else is redundant
        prog.empty_only = False
    return prog


# ---------------------------------------------------------------------------
# Required factors (for the literal prefilter, matcher/prefilter.py)
# ---------------------------------------------------------------------------


def _popcount(cs: int) -> int:
    return bin(cs).count("1")


def required_factors(
    prog: RuleProgram,
    min_len: int = 3,
    max_len: int = 12,
    max_class_size: int = 2,
) -> Optional[List[Tuple[Pos, ...]]]:
    """One necessary consecutive factor per branch, or None.

    A factor is a run of non-self-loop positions whose byte classes are
    narrow (size <= max_class_size, e.g. exact bytes or (?i) case pairs).
    Any match of the branch must contain the factor's classes consecutively,
    so "factor absent => branch cannot match" — the prefilter's soundness
    invariant. Runs break at self-loop positions (`C+` can repeat, so bytes
    around it are not consecutive); truncating a run keeps it necessary.
    Returns None when any branch lacks a qualifying run (the rule must then
    be matched against every line, prefilter or not).
    """
    if prog.always_match or prog.empty_only or not prog.branches:
        return None
    out: List[Tuple[Pos, ...]] = []
    for br in prog.branches:
        best: Tuple[Pos, ...] = ()
        run: List[Pos] = []
        for pos in list(br.positions) + [None]:  # sentinel flush
            if (
                pos is not None
                and not pos.loop
                and _popcount(pos.cs) <= max_class_size
            ):
                run.append(pos)
                continue
            if len(run) > len(best):
                best = tuple(run)
            run = []
        if len(best) < min_len:
            return None
        if len(best) > max_len:
            # middle slice: factor stays necessary, bounded state cost
            start = (len(best) - max_len) // 2
            best = best[start : start + max_len]
        out.append(best)
    return out


def factor_program(factor: Tuple[Pos, ...]) -> RuleProgram:
    """A factor as a one-branch unanchored search program."""
    return RuleProgram(
        branches=[Branch(tuple(Pos(p.cs) for p in factor), False, False)]
    )


# ---------------------------------------------------------------------------
# Packing: all rules → tensors
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CompiledRules:
    """Packed transition tensors for the batched shift-and match kernel.

    Word layout: `n_shards * words_per_shard` uint32 words; branch bit runs
    are contiguous and never straddle a shard boundary, so the word axis can
    be sharded across devices with no cross-shard carry.
    """

    n_rules: int
    n_shards: int
    words_per_shard: int
    n_classes: int                  # rows of b_table; class 0 is the pad class
    byte_to_class: np.ndarray       # [256] int32
    b_table: np.ndarray             # [n_classes, W] uint32
    shift_in: np.ndarray            # [W] uint32 — bit may receive a shifted-in bit
    inject_always: np.ndarray       # [W] uint32 — unanchored branch starts
    inject_start: np.ndarray        # [W] uint32 — ^-anchored branch starts (char 0)
    selfloop: np.ndarray            # [W] uint32
    accept_any: np.ndarray          # [W] uint32 — accept bits of unanchored-end branches
    accept_end: np.ndarray          # [W] uint32 — accept bits of $-anchored branches
    acc_word: np.ndarray            # [n_branches] int32 — accept word index per branch
    acc_mask: np.ndarray            # [n_branches] uint32 — accept bit mask per branch
    branch_rule: np.ndarray         # [n_branches] int32
    always_match: np.ndarray        # [n_rules] bool
    empty_only: np.ndarray          # [n_rules] bool
    device_ok: np.ndarray           # [n_rules] bool — False: host regex fallback
    unsupported: Dict[int, str] = dataclasses.field(default_factory=dict)
    # no branch straddles a 32-bit word boundary (pack_programs
    # align_branches=True and every branch fit): the match kernel can then
    # drop the cross-word carry — 3 of ~13 VPU ops per byte column
    carry_free: bool = False

    @property
    def n_words(self) -> int:
        return self.n_shards * self.words_per_shard

    @property
    def n_positions(self) -> int:
        # every position is either branch-initial (an inject bit) or shifted into
        used = self.shift_in | self.inject_always | self.inject_start
        return int(sum(bin(int(w)).count("1") for w in used))


def compile_rules(patterns: Sequence[str], n_shards=1) -> CompiledRules:
    """Compile a full ruleset into one packed tensor set.

    `patterns[i]` keeps rule id `i` end to end, so the caller can map match
    bits straight back to its RegexWithRate list (global + per-site rules
    concatenated, the way runner.py builds it). `n_shards="auto"` picks the
    shard count that minimizes total padded words for the match kernel.
    """
    programs: List[Optional[RuleProgram]] = []
    unsupported: Dict[int, str] = {}
    for i, pat in enumerate(patterns):
        try:
            programs.append(compile_rule(pat))
        except UnsupportedPattern as e:
            programs.append(None)
            unsupported[i] = str(e)
    return pack_programs(programs, n_shards=n_shards, unsupported=unsupported)


# The Pallas kernel pads each shard's word slab to this multiple. The VPU
# scan cost is ∝ the PADDED word count, so a small automaton (the fused
# prefilter's ~40-word stage 1) wastes 3-4x work at 128. 32 — the int8
# sublane tile, the tightest alignment every in-kernel slice (btab plane
# slices at multiples of W, [W, 8] mask rows, the [W, block] state) still
# satisfies — is the default; BANJAX_NFA_WORD_ALIGN=128 restores the old
# conservative padding if a Mosaic version rejects 32-row slabs.
def _parse_word_align(raw: "str | None") -> int:
    # Invalid values fall back to the default with a warning rather than
    # raising at import time (a typo'd env var must not take down the server).
    try:
        val = int(raw or 32)
    except (TypeError, ValueError):
        val = -1
    if val not in (32, 64, 128):
        if raw not in (None, "", "32"):
            import warnings

            warnings.warn(
                f"BANJAX_NFA_WORD_ALIGN={raw!r}: must be 32, 64, or 128 "
                "(multiples of the int8 sublane tile up to the lane width); "
                "falling back to 32",
                stacklevel=2,
            )
        val = 32
    return val


KERNEL_WORD_ALIGN = _parse_word_align(os.environ.get("BANJAX_NFA_WORD_ALIGN"))
_KERNEL_MAX_WPS = 512      # the kernel's per-shard VMEM comfort budget


def choose_shards(branch_lengths: Sequence[int], align: int = 0) -> int:
    """Exact-cost shard count: simulate the greedy branch packing for each
    candidate and minimize `n_shards * pad(real_words_per_shard, align)` —
    the dot-row count the kernel actually pays (a ceil(total/ns) estimate
    misses the packer's imbalance and can land just past a pad boundary)."""
    if not branch_lengths:
        return 1
    align = align or KERNEL_WORD_ALIGN
    order = sorted(branch_lengths, reverse=True)
    total = sum(order)
    best, best_cost = 1, None
    max_ns = max(1, -(-total // (128 * 32 // 2)))
    for ns in range(1, max_ns + 1):
        bits = [0] * ns
        for ln in order:
            s = min(range(ns), key=bits.__getitem__)
            bits[s] += ln
        wps = -(-max(bits) // 32)
        wps_p = max(align, -(-wps // align) * align)
        if wps_p > _KERNEL_MAX_WPS:
            continue
        cost = ns * wps_p
        if best_cost is None or cost < best_cost:
            best, best_cost = ns, cost
    return best


def pack_programs(
    programs: Sequence[Optional[RuleProgram]],
    n_shards=1,
    unsupported: Optional[Dict[int, str]] = None,
    byte_classes: Optional[Tuple[np.ndarray, int]] = None,
    align_branches: bool = False,
) -> CompiledRules:
    """Pack already-lowered rule programs into the transition tensors.

    Split out of compile_rules so synthetic programs (e.g. the literal
    prefilter's factor automata, matcher/prefilter.py) share the packing
    and the match kernels without a regex round-trip.

    `byte_classes` = (byte_to_class [256] int32, n_classes): use this
    pre-computed byte partition instead of deriving one from the programs'
    charsets. The partition must REFINE every position charset (all bytes of
    a class agree on membership) — e.g. the partition of a superset ruleset.
    This is what lets the two-stage prefilter share one encode pass with the
    full single-stage tensors: all three CompiledRules index the same class
    ids, so lines are classified once (matcher/prefilter.py).

    `align_branches=True` pads branch start bits so no branch of <=32
    positions straddles a word boundary; when every branch then fits,
    `carry_free` is set and the Pallas kernel drops its cross-word carry.
    Worth the padded words for narrow automata (the prefilter's stage 1,
    whose factors are <=12 positions); dense packing stays the default for
    the wide full-ruleset tensors.
    """
    n_rules = len(programs)
    unsupported = dict(unsupported or {})

    # gather branches: (rule_id, branch)
    all_branches: List[Tuple[int, Branch]] = []
    for i, prog in enumerate(programs):
        if prog is None:
            continue
        for br in prog.branches:
            all_branches.append((i, br))

    if n_shards == "auto":
        n_shards = choose_shards([len(b.positions) for _, b in all_branches])

    # shard assignment: greedy balance by bit length, branches atomic
    shard_bits = [0] * n_shards
    shard_members: List[List[int]] = [[] for _ in range(n_shards)]
    order = sorted(range(len(all_branches)),
                   key=lambda k: -len(all_branches[k][1].positions))
    for k in order:
        s = min(range(n_shards), key=lambda j: shard_bits[j])
        shard_members[s].append(k)
        shard_bits[s] += len(all_branches[k][1].positions)

    # bit assignment: per shard, branches in original order for determinism;
    # with align_branches, a <=32-position branch never straddles a word
    local_start: Dict[int, int] = {}
    shard_used = [0] * n_shards
    for s in range(n_shards):
        offset = 0
        for k in sorted(shard_members[s]):
            blen = len(all_branches[k][1].positions)
            if (
                align_branches and blen <= 32 and offset % 32
                and (offset % 32) + blen > 32
            ):
                offset = (offset + 31) // 32 * 32
            local_start[k] = offset
            offset += blen
        shard_used[s] = offset
    words_per_shard = max(1, (max(shard_used) + 31) // 32 if all_branches else 1)
    W = n_shards * words_per_shard
    bit_of_branch_start = [0] * len(all_branches)
    for s in range(n_shards):
        base = s * words_per_shard * 32
        for k in shard_members[s]:
            bit_of_branch_start[k] = base + local_start[k]
    carry_free = bool(all_branches) and all(
        (local_start[k] % 32) + len(all_branches[k][1].positions) <= 32
        for k in range(len(all_branches))
    )

    # byte equivalence classes over all distinct position charsets
    charsets: List[int] = []
    cs_index: Dict[int, int] = {}
    for _, br in all_branches:
        for p in br.positions:
            if p.cs not in cs_index:
                cs_index[p.cs] = len(charsets)
                charsets.append(p.cs)

    if byte_classes is not None:
        byte_to_class, n_classes = byte_classes
        byte_to_class = np.asarray(byte_to_class, dtype=np.int32)
        # refinement check: every class must be uniform w.r.t. every charset,
        # otherwise a representative-byte membership test would be wrong
        for cs in charsets:
            member = np.array([(cs >> b) & 1 for b in range(256)], dtype=np.int64)
            if len(set(zip(byte_to_class.tolist(), member.tolist()))) > len(
                set(byte_to_class.tolist())
            ):
                raise ValueError(
                    "byte_classes does not refine a position charset; "
                    "pack with the partition of a superset ruleset"
                )
    else:
        # signature of byte b = tuple of membership bits; identical signature
        # → same class. Class ids start at 1; 0 is the reserved pad class.
        sig_to_class: Dict[Tuple[int, ...], int] = {}
        byte_to_class = np.zeros(256, dtype=np.int32)
        for b in range(256):
            sig = tuple((cs >> b) & 1 for cs in charsets)
            cls = sig_to_class.get(sig)
            if cls is None:
                cls = len(sig_to_class) + 1
                sig_to_class[sig] = cls
            byte_to_class[b] = cls
        n_classes = len(sig_to_class) + 1

    b_table = np.zeros((n_classes, W), dtype=np.uint64)
    shift_in = np.zeros(W, dtype=np.uint64)
    inject_always = np.zeros(W, dtype=np.uint64)
    inject_start = np.zeros(W, dtype=np.uint64)
    selfloop = np.zeros(W, dtype=np.uint64)
    accept_any = np.zeros(W, dtype=np.uint64)
    accept_end = np.zeros(W, dtype=np.uint64)
    acc_word = np.zeros(len(all_branches), dtype=np.int32)
    acc_mask = np.zeros(len(all_branches), dtype=np.uint64)
    branch_rule = np.zeros(len(all_branches), dtype=np.int32)

    # one representative byte per class for charset membership tests
    class_rep: Dict[int, int] = {}
    for b in range(256):
        class_rep.setdefault(int(byte_to_class[b]), b)

    for k, (rule_id, br) in enumerate(all_branches):
        branch_rule[k] = rule_id
        start_bit = bit_of_branch_start[k]
        for j, pos in enumerate(br.positions):
            bit = start_bit + j
            w, o = bit // 32, bit % 32
            mask = np.uint64(1 << o)
            for cls, rep in class_rep.items():
                if cls == 0:
                    continue
                if (pos.cs >> rep) & 1:
                    b_table[cls, w] |= mask
            if j > 0:
                shift_in[w] |= mask
            else:
                if br.anchored_start:
                    inject_start[w] |= mask
                else:
                    inject_always[w] |= mask
            if pos.loop:
                selfloop[w] |= mask
        last_bit = start_bit + len(br.positions) - 1
        w, o = last_bit // 32, last_bit % 32
        mask = np.uint64(1 << o)
        if br.anchored_end:
            accept_end[w] |= mask
        else:
            accept_any[w] |= mask
        acc_word[k] = w
        acc_mask[k] = mask

    always = np.zeros(n_rules, dtype=bool)
    empty_only = np.zeros(n_rules, dtype=bool)
    device_ok = np.zeros(n_rules, dtype=bool)
    for i, prog in enumerate(programs):
        if prog is None:
            continue
        device_ok[i] = True
        always[i] = prog.always_match
        empty_only[i] = prog.empty_only

    return CompiledRules(
        n_rules=n_rules,
        n_shards=n_shards,
        words_per_shard=words_per_shard,
        n_classes=n_classes,
        byte_to_class=byte_to_class,
        b_table=b_table.astype(np.uint32),
        shift_in=shift_in.astype(np.uint32),
        inject_always=inject_always.astype(np.uint32),
        inject_start=inject_start.astype(np.uint32),
        selfloop=selfloop.astype(np.uint32),
        accept_any=accept_any.astype(np.uint32),
        accept_end=accept_end.astype(np.uint32),
        acc_word=acc_word,
        acc_mask=acc_mask.astype(np.uint32),
        branch_rule=branch_rule,
        always_match=always,
        empty_only=empty_only,
        device_ok=device_ok,
        unsupported=unsupported,
        carry_free=carry_free,
    )
