"""Host-side encoding: raw log lines → device tensors + parsed metadata.

The reference's consumeLine does all of this serially per line
(/root/reference/internal/regex_rate_limiter.go:113-172): split
"<epoch.frac> <ip> <rest>", parse the timestamp, split rest into
"<method> <host> <rest2>", drop stale lines, skip allowlisted IPs. The TPU
matcher performs the same parse on the host for a whole batch, then encodes
each matchable line's `rest` into byte-class ids (classes computed by the
rule compiler) for the device NFA pass.

Lines the device cannot decide route to the host regex path instead:
  * longer than the padded line length (truncation could lose a match);
  * containing non-ASCII bytes (Go/Python regexes are rune-based there,
    the device automaton is byte-based — route around the divergence).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from banjax_tpu.matcher.rulec import CompiledRules


@dataclasses.dataclass
class ParsedLine:
    """consumeLine's per-line fields (regex_rate_limiter.go:126-157)."""

    error: bool = False
    old_line: bool = False
    timestamp_ns: int = 0
    ip: str = ""
    host: str = ""
    rest: str = ""  # "<method> <host> <rest2>" — the regex haystack


def parse_line(line_text: str, now_unix: float, old_cutoff_seconds: float = 10.0) -> ParsedLine:
    """The exact split/parse/staleness sequence of consumeLine.

    This is the single source of the parse semantics — CpuMatcher and
    TpuMatcher both consume it, so the two paths cannot drift.
    """
    p = ParsedLine()
    time_ip_rest = line_text.split(" ", 2)
    if len(time_ip_rest) < 3:
        p.error = True
        return p
    try:
        # Go float64-multiply truncation; nan/inf timestamps are parse errors
        p.timestamp_ns = int(float(time_ip_rest[0]) * 1e9)
    except (ValueError, OverflowError):
        p.error = True
        return p
    p.ip = time_ip_rest[1]
    method_url_rest = time_ip_rest[2].split(" ", 2)
    if len(method_url_rest) < 3:
        p.error = True
        return p
    p.host = method_url_rest[1]
    p.rest = time_ip_rest[2]
    if now_unix - p.timestamp_ns / 1e9 > old_cutoff_seconds:
        p.old_line = True
    return p


def encode_for_match(
    compiled: CompiledRules,
    lines: Sequence[Union[str, bytes]],
    max_len: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Encode byte strings → (cls_ids [B, max_len], lens [B], host_eval [B]).

    Pad bytes get class 0, whose b_table row is all zeros, so device state
    collapses past end-of-line with no explicit length masking.
    """
    B = len(lines)
    cls_ids = np.zeros((B, max_len), dtype=np.int32)
    lens = np.zeros(B, dtype=np.int32)
    host_eval = np.zeros(B, dtype=bool)
    table = compiled.byte_to_class
    for i, raw in enumerate(lines):
        if isinstance(raw, str):
            raw = raw.encode("utf-8", "surrogatepass")
        n = len(raw)
        if n > max_len:
            host_eval[i] = True
            continue
        arr = np.frombuffer(raw, dtype=np.uint8)
        if n and arr.max() > 0x7F:
            host_eval[i] = True
            continue
        cls_ids[i, :n] = table[arr]
        lens[i] = n
    return cls_ids, lens, host_eval
