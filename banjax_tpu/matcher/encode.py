"""Host-side encoding: raw log lines → device tensors + parsed metadata.

The reference's consumeLine does all of this serially per line
(/root/reference/internal/regex_rate_limiter.go:113-172): split
"<epoch.frac> <ip> <rest>", parse the timestamp, split rest into
"<method> <host> <rest2>", drop stale lines, skip allowlisted IPs. The TPU
matcher performs the same parse on the host for a whole batch, then encodes
each matchable line's `rest` into byte-class ids (classes computed by the
rule compiler) for the device NFA pass.

Lines the device cannot decide route to the host regex path instead:
  * longer than the padded line length (truncation could lose a match);
  * containing non-ASCII bytes (Go/Python regexes are rune-based there,
    the device automaton is byte-based — route around the divergence).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from banjax_tpu.matcher.rulec import CompiledRules


@dataclasses.dataclass
class ParsedLine:
    """consumeLine's per-line fields (regex_rate_limiter.go:126-157)."""

    error: bool = False
    old_line: bool = False
    timestamp_ns: int = 0
    ip: str = ""
    host: str = ""
    rest: str = ""  # "<method> <host> <rest2>" — the regex haystack


def parse_line(line_text: str, now_unix: float, old_cutoff_seconds: float = 10.0) -> ParsedLine:
    """The exact split/parse/staleness sequence of consumeLine.

    This is the single source of the parse semantics — CpuMatcher and
    TpuMatcher both consume it, so the two paths cannot drift.
    """
    p = ParsedLine()
    time_ip_rest = line_text.split(" ", 2)
    if len(time_ip_rest) < 3:
        p.error = True
        return p
    try:
        # Go float64-multiply truncation; nan/inf timestamps are parse errors
        p.timestamp_ns = int(float(time_ip_rest[0]) * 1e9)
    except (ValueError, OverflowError):
        p.error = True
        return p
    p.ip = time_ip_rest[1]
    method_url_rest = time_ip_rest[2].split(" ", 2)
    if len(method_url_rest) < 3:
        p.error = True
        return p
    p.host = method_url_rest[1]
    p.rest = time_ip_rest[2]
    if now_unix - p.timestamp_ns / 1e9 > old_cutoff_seconds:
        p.old_line = True
    return p


def encode_lines(
    lines: Sequence[Union[str, bytes]], max_len: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Byte strings → ([B, max_len] uint8 byte matrix, lens, host_eval).

    Vectorized (no per-line numpy work): one blob concatenation plus fancy
    indexing — the host side of the match path runs at memory speed instead
    of the Python interpreter's. Class mapping is per-ruleset and therefore
    separate (encode_for_match); the byte matrix itself is ruleset-agnostic
    so two-stage matching (matcher/prefilter.py) encodes bytes once.
    """
    B = len(lines)
    raws = [
        s.encode("utf-8", "surrogatepass") if isinstance(s, str) else s
        for s in lines
    ]
    lens_all = np.fromiter((len(r) for r in raws), dtype=np.int64, count=B)
    host_eval = lens_all > max_len

    keep_idx = np.flatnonzero(~host_eval)
    kept_lens = lens_all[keep_idx]
    blob = b"".join(raws[i] for i in keep_idx)
    flat = np.frombuffer(blob, dtype=np.uint8)

    mat = np.zeros((keep_idx.size, max_len), dtype=np.uint8)
    if flat.size:
        starts = np.zeros(keep_idx.size, dtype=np.int64)
        np.cumsum(kept_lens[:-1], out=starts[1:])
        rows = np.repeat(np.arange(keep_idx.size), kept_lens)
        cols = np.arange(flat.size, dtype=np.int64) - np.repeat(starts, kept_lens)
        mat[rows, cols] = flat

    non_ascii = (mat > 0x7F).any(axis=1)
    if non_ascii.any():
        host_eval[keep_idx[non_ascii]] = True
        mat[non_ascii] = 0
        kept_lens = np.where(non_ascii, 0, kept_lens)

    bytes_mat = np.zeros((B, max_len), dtype=np.uint8)
    bytes_mat[keep_idx] = mat
    lens = np.zeros(B, dtype=np.int32)
    lens[keep_idx] = kept_lens.astype(np.int32)
    return bytes_mat, lens, host_eval


def classify_bytes(
    compiled: CompiledRules, bytes_mat: np.ndarray, lens: np.ndarray
) -> np.ndarray:
    """[B, L] bytes → [B, L] int32 class ids; pad positions get class 0."""
    cls = compiled.byte_to_class[bytes_mat]
    cls[np.arange(bytes_mat.shape[1])[None, :] >= lens[:, None]] = 0
    return np.ascontiguousarray(cls, dtype=np.int32)


def encode_for_match(
    compiled: CompiledRules,
    lines: Sequence[Union[str, bytes]],
    max_len: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Encode byte strings → (cls_ids [B, max_len], lens [B], host_eval [B]).

    Pad bytes get class 0, whose b_table row is all zeros, so device state
    collapses past end-of-line with no explicit length masking.
    """
    bytes_mat, lens, host_eval = encode_lines(lines, max_len)
    return classify_bytes(compiled, bytes_mat, lens), lens, host_eval
