"""The Matcher seam: the contract of consumeLine, not its code.

Reference behavior: /root/reference/internal/regex_rate_limiter.go:80-269.
A Matcher consumes parsed log lines and produces per-line ConsumeLineResult
records plus the side effects BanOrChallengeIp + LogRegexBan through the
Banner boundary. Two implementations exist:

  * CpuMatcher (cpu_ref.py) — line-at-a-time, semantics-identical to the Go
    loop; the default and the correctness oracle.
  * TpuMatcher (runner.py)  — batches lines into device tensors, matches all
    rules at once with the Pallas NFA kernel, and runs the fixed-window
    counters on device; selected with `matcher: tpu` in banjax-config.yaml.

Both must produce byte-identical Decision streams for the same input.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from banjax_tpu.decisions.rate_limit import RateLimitResult


@dataclasses.dataclass
class RuleResult:
    """regex_rate_limiter.go:87-93."""

    rule_name: str = ""
    regex_match: bool = False
    skip_host: bool = False
    seen_ip: bool = False
    rate_limit_result: Optional[RateLimitResult] = None


@dataclasses.dataclass
class ConsumeLineResult:
    """regex_rate_limiter.go:80-85."""

    error: bool = False
    old_line: bool = False
    exempted: bool = False
    rule_results: List[RuleResult] = dataclasses.field(default_factory=list)


class Matcher:
    """One log line in, one ConsumeLineResult out (plus Banner side effects)."""

    def consume_line(self, line_text: str, now_unix: Optional[float] = None) -> ConsumeLineResult:
        raise NotImplementedError

    def close(self) -> None:
        """Flush any buffered device batches (no-op for the CPU matcher)."""
