"""The Matcher seam: the contract of consumeLine, not its code.

Reference behavior: /root/reference/internal/regex_rate_limiter.go:80-269.
A Matcher consumes parsed log lines and produces per-line ConsumeLineResult
records plus the side effects BanOrChallengeIp + LogRegexBan through the
Banner boundary. Two implementations exist:

  * CpuMatcher (cpu_ref.py) — line-at-a-time, semantics-identical to the Go
    loop; the default and the correctness oracle.
  * TpuMatcher (runner.py)  — batches lines into device tensors, matches all
    rules at once with the Pallas NFA kernel, and runs the fixed-window
    counters on device; selected with `matcher: tpu` in banjax-config.yaml.

Both must produce byte-identical Decision streams for the same input.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import List, Optional

from banjax_tpu.decisions.rate_limit import RateLimitResult

_log = logging.getLogger(__name__)

_stats_init_lock = threading.Lock()


@dataclasses.dataclass
class RuleResult:
    """regex_rate_limiter.go:87-93."""

    rule_name: str = ""
    regex_match: bool = False
    skip_host: bool = False
    seen_ip: bool = False
    rate_limit_result: Optional[RateLimitResult] = None


@dataclasses.dataclass
class ConsumeLineResult:
    """regex_rate_limiter.go:80-85."""

    error: bool = False
    old_line: bool = False
    exempted: bool = False
    rule_results: List[RuleResult] = dataclasses.field(default_factory=list)


class Matcher:
    """One log line in, one ConsumeLineResult out (plus Banner side effects)."""

    @property
    def stats(self):
        """Runtime counters surfaced in the 29s metrics line (obs/stats.py).
        Creation is lock-guarded: the metrics thread and the tailer thread
        can both hit a fresh matcher concurrently."""
        s = getattr(self, "_stats", None)
        if s is None:
            with _stats_init_lock:
                s = getattr(self, "_stats", None)
                if s is None:
                    from banjax_tpu.obs.stats import MatcherStats

                    s = self._stats = MatcherStats()
        return s

    def consume_line(self, line_text: str, now_unix: Optional[float] = None) -> ConsumeLineResult:
        raise NotImplementedError

    def consume_lines(
        self, lines: List[str], now_unix: Optional[float] = None
    ) -> List[ConsumeLineResult]:
        """Batch entry point. The TPU matcher overrides this with one device
        pass per batch; the default preserves the serial reference semantics,
        including per-line fault isolation (one bad line loses only itself)."""
        import time as _time

        t0 = _time.perf_counter()
        results = []
        for line in lines:
            try:
                results.append(self.consume_line(line, now_unix))
            except Exception:  # noqa: BLE001 — isolate faults per line
                _log.exception("error consuming log line")
                results.append(ConsumeLineResult(error=True))
        self.stats.record_batch(len(lines), _time.perf_counter() - t0)
        return results

    def close(self) -> None:
        """Flush any buffered device batches (no-op for the CPU matcher)."""
