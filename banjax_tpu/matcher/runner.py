"""TpuMatcher: the batched device-backed implementation of the Matcher seam.

Pipeline per batch (SURVEY.md §7.1 / BASELINE.json north star):

  host parse (encode.parse_line, the exact consumeLine splits)
    → byte-class encode → device NFA match (nfa_jax.match_batch: all lines ×
      all rules in one jitted shift-and scan)
    → host fixed-window pass in original line order (the authoritative
      RegexRateLimitStates — byte-identical window semantics by construction)
    → Banner side effects (BanOrChallengeIp + LogRegexBan), identical call
      sequence to the CPU reference path.

The device decides only the regex-match bitmap — the O(lines × rules) hot
loop of /root/reference/internal/regex_rate_limiter.go:234. Rule/line cases
the device can't decide exactly (rules rulec can't lower; non-ASCII or
over-length lines) fall back to host `re` per rule or per line, so the
observable Decision stream is byte-identical to CpuMatcher for any input.

Selected by `matcher: tpu` in banjax-config.yaml (the Matcher interface
flag named in BASELINE.json); CpuMatcher remains the default.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from banjax_tpu.config.schema import Config, RegexWithRate
from banjax_tpu.matcher.kernels import nfa_match as pallas_nfa
from banjax_tpu.decisions.rate_limit import (
    RateLimitResult,
    RegexRateLimitStates,
)
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.effectors.banner import BannerInterface
from banjax_tpu.matcher import nfa_jax
from banjax_tpu.matcher.api import ConsumeLineResult, Matcher, RuleResult
from banjax_tpu.matcher.cpu_ref import OLD_LINE_CUTOFF_SECONDS
from banjax_tpu.matcher.encode import ParsedLine, encode_for_match, parse_line
from banjax_tpu.matcher.workset import (
    CompositeWork,
    LazyResults,
    ListWork,
    NativeWork,
    unique_spans,
)
from banjax_tpu.matcher.rulec import compile_rules
from banjax_tpu.obs import flightrec, provenance, trace
from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.breaker import CLOSED, CircuitBreaker
from banjax_tpu.resilience.health import HealthRegistry, HealthStatus

log = logging.getLogger(__name__)

_MIN_BUCKET = 64

# process-wide warn-once for the drain_resolve_depth/single-kernel no-op
# (tests construct many matchers; one log line is the useful signal)
_DEPTH_IGNORED_WARNED = False


class TpuMatcher(Matcher):
    # True when drain_resolve_depth > 1 is configured but the active
    # single-kernel path makes it a no-op (SingleKernelDepthIgnored)
    single_kernel_depth_ignored = False

    def __init__(
        self,
        config: Config,
        banner: BannerInterface,
        decision_lists: StaticDecisionLists,
        rate_limit_states: RegexRateLimitStates,
        n_shards: int = 1,
        health: Optional[HealthRegistry] = None,
    ):
        self.config = config
        self.banner = banner
        self.decision_lists = decision_lists
        self.rate_limit_states = rate_limit_states

        # circuit breaker around the device batch path: consecutive device
        # failures (or latency-budget breaches) trip it OPEN and every
        # batch routes to the CPU reference matcher until a half-open
        # probe succeeds — a wedged TPU degrades throughput, never drops
        # log lines (resilience/breaker.py)
        self.breaker = CircuitBreaker(
            failure_threshold=getattr(config, "breaker_failure_threshold", 3),
            recovery_seconds=getattr(config, "breaker_recovery_seconds", 30.0),
            window_size=getattr(config, "breaker_window_size", 0),
            name="matcher-device",
            # breaker trips land in the trace ring as instant events so a
            # Perfetto view shows WHEN degraded mode started relative to
            # the batch spans around it, and arm the incident flight
            # recorder (debounced; no-op when none is installed)
            on_trip=self._on_breaker_trip,
        )
        self._latency_budget_s = (
            getattr(config, "matcher_latency_budget_ms", 0.0) or 0.0
        ) / 1e3
        # when the config budget is unset, the pipeline scheduler installs
        # a source deriving it from the measured device p99 (ROADMAP
        # breaker-tuning item; obs/stats.py suggested_latency_budget_s)
        self._latency_budget_source = None
        self.fallback_batches = 0  # batches served by the CPU fallback
        # latency-budget breaches counted as breaker failures — the
        # observable validation of the derived budget the ROADMAP carried
        # (banjax_matcher_budget_trips_total; feeds the SLO engine)
        self.budget_trips = 0
        # two-phase fused chunks committed through the streaming pipeline
        # (match dispatched at submit, window commit at drain) and how
        # often one fell back to the classic replay mid-pipeline
        self.pipelined_fused_chunks = 0
        self.pipelined_fused_fallbacks = 0
        # pipeline_fused=false restores the PR 2 behavior: the split
        # protocol always takes the classic bitmap path
        self._pipeline_fused = bool(getattr(config, "pipeline_fused", True))
        # resolve-ahead depth for the fused drain commit: at depth d the
        # drain keeps up to d-1 resolved chunks pending, so chunk i+1's
        # window program (B) is on the device while chunk i's events
        # decode/replay — the ~65 ms fixed d2h pull overlaps instead of
        # serializing the drain thread.  1 restores the serial drain.
        self._drain_resolve_depth = max(
            1, int(getattr(config, "drain_resolve_depth", 2))
        )
        self.drain_resolve_overlap_ms_ewma: Optional[float] = None
        # batches whose device-window apply is deferred to their drain
        # turn (classic-pend fallbacks): while any is outstanding, the
        # single-kernel path must not commit at submit (see
        # _single_kernel_ordered) or window updates would cross batches
        # out of admission order
        self._drain_window_lock = threading.Lock()
        self._drain_window_batches = 0
        self._cpu_fallback = None
        self._health_registry = health
        self._health = health.register("matcher") if health is not None else None

        # Rule table: per-site rules first, then global — rule id i here is
        # column i of the device match bitmap, end to end.
        self._entries: List[Tuple[Optional[str], RegexWithRate]] = []
        self._per_site_idx: Dict[str, List[int]] = {}
        for site, rules in config.per_site_regexes_with_rates.items():
            for r in rules:
                self._per_site_idx.setdefault(site, []).append(len(self._entries))
                self._entries.append((site, r))
        self._global_idx: List[int] = []
        for r in config.regexes_with_rates:
            self._global_idx.append(len(self._entries))
            self._entries.append((None, r))

        # mesh mode: rule-parallel degree fixes the compile shard count so
        # each rp member owns exactly one self-contained word slab
        self._mesh = None
        self._mesh_rp = 0
        mesh_devices = getattr(config, "matcher_mesh_devices", 0) or 0
        if mesh_devices > 0:
            n_avail = len(jax.devices())
            if mesh_devices > n_avail:
                log.warning(
                    "matcher_mesh_devices=%d but only %d JAX devices are "
                    "attached; running single-device", mesh_devices, n_avail,
                )
            else:
                rp = getattr(config, "matcher_mesh_rp", 0) or 0
                if rp == 0:
                    rp = 1
                    while rp * 2 <= min(4, mesh_devices) and mesh_devices % (rp * 2) == 0:
                        rp *= 2
                if mesh_devices % rp != 0:
                    raise ValueError(
                        f"matcher_mesh_rp {rp} does not divide "
                        f"matcher_mesh_devices {mesh_devices}"
                    )
                self._mesh_rp = rp
                n_shards = rp

        self.compiled = compile_rules(
            [r.regex_string for _, r in self._entries], n_shards=n_shards
        )
        for i, reason in self.compiled.unsupported.items():
            log.info(
                "rule %r falls back to the host regex path: %s",
                self._entries[i][1].rule, reason,
            )
        self._host_rule_idx = [
            i for i in range(len(self._entries)) if not self.compiled.device_ok[i]
        ]
        self._params = nfa_jax.match_params(self.compiled)
        self._max_len = config.matcher_max_line_len
        self._max_batch = max(_MIN_BUCKET, config.matcher_batch_lines)

        # native C batch parse+encode (banjax_tpu/native): ~16x the Python
        # per-line parse loop; per-line semantics identical (defer contract)
        self._native = False
        self._parse_scratch = None
        self._dedup_scratch = None
        # allowlist results per distinct (host, ip), valid for one
        # static-lists snapshot (cleared on hot reload / size bound)
        self._allow_cache: Dict[Tuple[str, str], bool] = {}
        self._allow_cache_snap = None
        if getattr(config, "matcher_native_parse", True):
            from banjax_tpu import native as _native

            self._native = _native.available()
            if self._native:
                # reused output buffers: fresh allocations cost ~15 ms in
                # page faults per 65k batch; each batch is fully consumed
                # (all reads are copies) before the next parse reuses them
                self._parse_scratch = _native.ParseScratch()
                self._dedup_scratch = _native.DedupScratch()
            else:
                log.info("native fastparse unavailable; Python parse path")

        # device backend: the Pallas kernel where it pays (TPU), the XLA
        # scan elsewhere; "pallas-interpret" is the CI path
        backend = getattr(config, "matcher_backend", "auto") or "auto"
        self._pallas_prep = None
        self._pallas_interpret = backend == "pallas-interpret"
        if backend == "pallas" and jax.default_backend() != "tpu":
            # compiled Mosaic can't lower off-TPU; failing per-batch at
            # runtime would drop every log line, so degrade at init instead
            log.warning(
                "matcher_backend=pallas requested but the JAX backend is %s; "
                "falling back to the XLA scan", jax.default_backend(),
            )
            backend = "xla"
        want_pallas = backend in ("pallas", "pallas-interpret") or (
            backend == "auto" and jax.default_backend() == "tpu"
        )
        # device-resident window counters (matcher/windows.py): authoritative
        # for the regex rules when enabled; the host RegexRateLimitStates is
        # bypassed (introspection goes through self.device_windows)
        self.device_windows = None
        self._active_table = None
        self.traffic_sketch = None
        self._slot_admission = False
        self._admission_min_estimate = 1
        self._host_row: Dict[str, int] = {}
        if getattr(config, "matcher_device_windows", False):
            from banjax_tpu.matcher.windows import DeviceWindows

            self.device_windows = DeviceWindows(
                [r for _, r in self._entries],
                capacity=getattr(config, "matcher_window_capacity", 0),
                native_slotmgr=getattr(config, "slotmgr_native", True),
                warm_tier_enabled=getattr(config, "warm_tier_enabled", False),
                warm_tier_capacity=getattr(
                    config, "warm_tier_capacity", 1 << 20
                ),
            )
            # active_table[h, rid]: rule rid applies to lines of host row h
            # (per-site rules of that host + global rules), minus
            # hosts_to_skip — the per-site-then-global loop of
            # regex_rate_limiter.go:175-211 as a device mask
            hosts = sorted(
                set(self._per_site_idx)
                | {h for _, r in self._entries for h in r.hosts_to_skip}
            )
            self._host_row = {h: i + 1 for i, h in enumerate(hosts)}
            n_rules = len(self._entries)
            table = np.zeros((len(hosts) + 1, max(1, n_rules)), dtype=bool)
            for row_host, row in [(None, 0)] + list(self._host_row.items()):
                ids = (
                    self._per_site_idx.get(row_host, []) if row_host else []
                ) + self._global_idx
                for idx in ids:
                    if row_host and self._entries[idx][1].hosts_to_skip.get(row_host):
                        continue
                    table[row, idx] = True
            self._active_table = jnp.asarray(table)

            # traffic introspection plane (obs/sketch.py): count-min +
            # HLL + per-rule pressure folded in-stream per chunk, keyed
            # on the window slot ids already bound for the device — a
            # read-only telemetry sibling of the window state (ROADMAP
            # mega-state item 1 builds its cold admission on the same
            # structure)
            if getattr(config, "traffic_sketch_enabled", True):
                from banjax_tpu.obs.sketch import TrafficSketch

                self.traffic_sketch = TrafficSketch(
                    [r.rule for _, r in self._entries],
                    depth=getattr(config, "traffic_sketch_depth", 4),
                    width=getattr(config, "traffic_sketch_width", 8192),
                    hll_p=getattr(config, "traffic_sketch_hll_p", 12),
                    pull_seconds=getattr(
                        config, "traffic_sketch_pull_seconds", 5.0
                    ),
                    topk=getattr(config, "traffic_sketch_topk", 32),
                    max_candidates=getattr(
                        config, "traffic_sketch_candidates", 8192
                    ),
                )

            # cold-tier slot admission (mega-state tiering): an UNSEEN ip
            # claims a hot-tier slot only when the sketch estimate says it
            # plausibly crosses the cheapest rule threshold.  Requires the
            # sketch (the estimates) — admission silently stays off
            # without it.  min_estimate 0 derives the cheapest threshold
            # from the ruleset: min(hits_per_interval) + 1 is the
            # earliest row count at which ANY rule can fire.
            self._slot_admission = bool(
                getattr(config, "slot_admission_enabled", False)
            ) and self.traffic_sketch is not None
            me = int(getattr(config, "slot_admission_min_estimate", 0))
            if me <= 0:
                me = max(
                    1,
                    min(
                        (r.hits_per_interval for _, r in self._entries),
                        default=0,
                    ) + 1,
                )
            self._admission_min_estimate = me

        self._mesh_matcher = None
        if self._mesh_rp:
            from banjax_tpu.parallel.mesh import ShardedMatchBackend, make_mesh

            self._mesh = make_mesh(mesh_devices, rp=self._mesh_rp)
            if self._pallas_interpret:
                mesh_backend = "pallas-interpret"
            elif want_pallas:
                mesh_backend = "pallas"
            else:
                mesh_backend = "xla"

            # fused two-stage under the mesh: stage 1 replicated, stage 2
            # packed to exactly rp word slabs, shared byte classes with the
            # full single-stage tensors (one encode feeds everything)
            mesh_plan = None
            if getattr(config, "matcher_prefilter", True):
                from banjax_tpu.matcher.prefilter import build_plan

                try:
                    mesh_plan = build_plan(
                        [r.regex_string for _, r in self._entries],
                        byte_classes=(
                            self.compiled.byte_to_class,
                            self.compiled.n_classes,
                        ),
                        stage2_shards=self._mesh_rp,
                    )
                except Exception:  # noqa: BLE001 — plan bug must not kill the matcher
                    log.exception("mesh prefilter plan failed; single-stage")

            # block granularity only matters for the compiled kernel; the
            # XLA/interpret bodies shouldn't pad every batch to dp*128 rows
            mesh_health = (
                self._health_registry.register("matcher-mesh")
                if self._health_registry is not None else None
            )

            def _mk(backend):
                return ShardedMatchBackend(
                    self.compiled, self._mesh, self._max_len, backend=backend,
                    block_b=128 if backend == "pallas" else 8,
                    plan=mesh_plan, health=mesh_health,
                )

            try:
                self._mesh_matcher = _mk(mesh_backend)
            except pallas_nfa.PallasUnsupported as e:
                log.info(
                    "mesh pallas backend unavailable (%s); XLA-scan mesh", e
                )
                self._mesh_matcher = _mk("xla")
            log.info(
                "matcher mesh: dp=%d rp=%d backend=%s prefilter=%s",
                self._mesh.shape["dp"], self._mesh_rp,
                self._mesh_matcher.backend, mesh_plan is not None,
            )

        if want_pallas and self._mesh_matcher is None:
            try:
                # re-shard for the kernel's VMEM/padding economics; byte
                # classes are shard-independent by rulec construction —
                # encode uses self.compiled's table, so check the invariant
                # rather than trust it
                comp = compile_rules(
                    [r.regex_string for _, r in self._entries], n_shards="auto"
                )
                if not np.array_equal(
                    comp.byte_to_class, self.compiled.byte_to_class
                ):
                    raise pallas_nfa.PallasUnsupported(
                        "byte-class table changed across re-shard"
                    )
                self._pallas_prep = pallas_nfa.prepare(comp)
            except pallas_nfa.PallasUnsupported as e:
                log.info("pallas matcher backend unavailable (%s); using XLA scan", e)

        # two-stage literal prefilter (matcher/prefilter.py): compile-time
        # rearrangement, bit-identical output; auto-disabled when the
        # ruleset has too few filterable rules. The fused variant shares
        # this matcher's byte classes, so the native parse's encode feeds
        # it directly and the whole two-stage pipeline is one device call.
        self._prefilter = None
        if getattr(config, "matcher_prefilter", True) and self._mesh_matcher is None:
            from banjax_tpu.matcher.prefilter import FusedPrefilter, build_plan

            try:
                plan = build_plan(
                    [r.regex_string for _, r in self._entries],
                    byte_classes=(
                        self.compiled.byte_to_class, self.compiled.n_classes
                    ),
                )
            except Exception:  # noqa: BLE001 — a plan bug must not kill the matcher
                log.exception("prefilter plan construction failed; single-stage")
                plan = None
            if plan is not None:
                if self._pallas_interpret:
                    pf_backend = "pallas-interpret"
                elif self._pallas_prep is not None:
                    pf_backend = "pallas"
                else:
                    pf_backend = "xla"
                try:
                    self._prefilter = FusedPrefilter(
                        plan, pf_backend,
                        cand_frac=getattr(
                            config, "matcher_prefilter_cand_frac", 0.125
                        ),
                    )
                except pallas_nfa.PallasUnsupported as e:
                    log.info("prefilter unavailable (%s); single-stage", e)

        # per-host per-site-then-global rule order as index arrays, so the
        # replay loops touch only matched rules instead of iterating the
        # whole ruleset per line (regex_rate_limiter.go:175-211 order)
        self._rule_pos_cache: Dict[str, Dict[int, int]] = {}
        self._global_pos = {int(x): k for k, x in enumerate(self._global_idx)}

        # fully-fused matcher+windows pipeline: one device dispatch per
        # batch when both the fused prefilter and device windows are on and
        # every rule is device-decidable (host-fallback rules need the
        # classic bitmap path)
        self._fw_pipeline = None
        if (
            self.device_windows is not None
            and self._prefilter is not None
            and not self._host_rule_idx
        ):
            from banjax_tpu.matcher.fused_windows import FusedWindowsPipeline

            single, scan_interpret = self._resolve_single_kernel(config)
            self._fw_pipeline = FusedWindowsPipeline(
                self._prefilter, self.device_windows, self._active_table,
                self.compiled.n_rules, single_kernel=single,
                scan_interpret=scan_interpret,
                traffic_sketch=self.traffic_sketch,
            )
            log.info(
                "fused matcher+windows pipeline active (%s)",
                "single-kernel" if single else "two-program",
            )

    def _resolve_single_kernel(self, config) -> Tuple[bool, bool]:
        """Resolve `pallas_single_kernel` for this backend: "auto" turns
        the one-program fused path on whenever the Pallas window-scan
        kernel lowers (compiled Mosaic on TPU, interpret-mode elsewhere —
        the CI path), proven by a bit-exact selftest against the XLA
        lax.scan.  A lowering/selftest failure downgrades gracefully to
        the two-program path with a health-registry note, so a Mosaic
        regression costs throughput, never correctness."""
        sk_cfg = (getattr(config, "pallas_single_kernel", "auto") or "auto")
        scan_interpret = bool(
            self._pallas_interpret or jax.default_backend() != "tpu"
        )
        comp = (
            self._health_registry.register("matcher-single-kernel")
            if self._health_registry is not None else None
        )
        if sk_cfg == "off":
            if comp is not None:
                comp.ok("pallas_single_kernel: off (two-program path)")
            return False, scan_interpret
        try:
            from banjax_tpu.matcher.kernels import fused_match_window

            fused_match_window.scan_selftest(scan_interpret)
        except Exception as e:  # noqa: BLE001 — downgrade, never fail the matcher
            msg = (
                f"single-kernel window-scan unavailable ({e}); "
                "two-program fused path"
            )
            (log.warning if sk_cfg == "on" else log.info)(msg)
            if comp is not None:
                comp.degraded(msg)
            return False, scan_interpret
        # PR 7 silently ignored drain_resolve_depth on this path (the
        # drain has no program-B dispatch left to overlap): surface the
        # no-op as a warn-once + health note + SingleKernelDepthIgnored
        # gauge instead of letting the knob look live
        depth_note = ""
        if self._drain_resolve_depth > 1:
            self.single_kernel_depth_ignored = True
            depth_note = (
                f"; drain_resolve_depth={self._drain_resolve_depth} is a "
                "no-op here (no program-B dispatch to overlap)"
            )
            global _DEPTH_IGNORED_WARNED
            if not _DEPTH_IGNORED_WARNED:
                _DEPTH_IGNORED_WARNED = True
                log.warning(
                    "drain_resolve_depth=%d is configured but the "
                    "single-kernel fused path commits at submit — the "
                    "resolve-ahead depth is a no-op (set "
                    "pallas_single_kernel: off to use it, or drop the key)",
                    self._drain_resolve_depth,
                )
        if comp is not None:
            comp.ok(
                "single-kernel fused path active "
                + ("(interpret scan)" if scan_interpret else "(compiled scan)")
                + depth_note
            )
        return True, scan_interpret

    # ---- Matcher API ----

    def consume_line(self, line_text: str, now_unix: Optional[float] = None) -> ConsumeLineResult:
        return self.consume_lines([line_text], now_unix)[0]

    def consume_lines(
        self, lines: Sequence[str], now_unix: Optional[float] = None,
        _fused_ok: bool = True,
    ) -> List[ConsumeLineResult]:
        """Breaker-guarded batch entry point.

        OPEN → the batch goes straight to the CPU reference matcher (the
        correctness oracle: byte-identical Decision stream, host-only).
        CLOSED/HALF_OPEN → the device path runs; a device exception or a
        latency-budget breach records a failure, and an excepting batch is
        re-run on the CPU fallback so its lines are never dropped.  Device
        dispatch happens before any Banner side effect fires, so the
        failure-then-fallback rerun cannot double-apply effects.
        """
        t0 = time.perf_counter()
        try:
            if not self.breaker.allow():
                return self._fallback_consume(lines, now_unix)
            try:
                results = self._consume_lines_inner(
                    lines, now_unix, fused_ok=_fused_ok
                )
            except Exception:  # noqa: BLE001 — device failure → breaker + fallback
                log.exception(
                    "device matcher batch failed; re-running batch on the "
                    "CPU reference matcher"
                )
                self.breaker.record_failure()
                return self._fallback_consume(lines, now_unix)
            budget = self.effective_latency_budget_s()
            if budget and time.perf_counter() - t0 > budget:
                self.budget_trips += 1
                self.breaker.record_failure()
            else:
                self.breaker.record_success()
            self._note_health()
            return results
        finally:
            self.stats.record_batch(len(lines), time.perf_counter() - t0)

    def consume_lines_serial(
        self, lines: Sequence[str], now_unix: Optional[float] = None
    ) -> List[ConsumeLineResult]:
        """consume_lines with the fused single-dispatch path disabled —
        the streaming scheduler's generic drain uses this: a generic batch
        drains on the drain thread while LATER batches' two-phase chunks
        already hold fused-pipeline order turns, so an inline fused burst
        here would wait on turns that only release after this very drain
        completes (deadlock).  The classic bitmap path it takes instead is
        differentially proven byte-identical."""
        return self.consume_lines(lines, now_unix, _fused_ok=False)

    def effective_latency_budget_s(self) -> float:
        """The breaker's per-batch latency budget: the configured
        `matcher_latency_budget_ms` when set, else the pipeline-derived
        value (3x EWMA device p99, floor 50 ms) when a scheduler has
        installed a source, else 0 (budget check disabled)."""
        if self._latency_budget_s:
            return self._latency_budget_s
        src = self._latency_budget_source
        if src is None:
            return 0.0
        try:
            return max(0.0, float(src()))
        except Exception:  # noqa: BLE001 — a stats bug must not break consume
            log.exception("latency budget source failed; budget disabled")
            return 0.0

    def set_latency_budget_source(self, fn) -> None:
        self._latency_budget_source = fn

    def note_device_outcome(self, elapsed_s: float, ok: bool) -> None:
        """Breaker + health accounting for an externally-driven device
        dispatch (the pipeline scheduler's submit/collect stages)."""
        if not ok:
            self.breaker.record_failure()
        else:
            budget = self.effective_latency_budget_s()
            if budget and elapsed_s > budget:
                self.budget_trips += 1
                self.breaker.record_failure()
            else:
                self.breaker.record_success()
        self._note_health()

    def _on_breaker_trip(self, name: str) -> None:
        trace.instant("breaker-trip", {"breaker": name})
        flightrec.notify("breaker-trip", name)

    def _fallback_matcher(self):
        if self._cpu_fallback is None:
            from banjax_tpu.matcher.cpu_ref import CpuMatcher

            self._cpu_fallback = CpuMatcher(
                self.config, self.banner, self.decision_lists,
                self.rate_limit_states,
            )
        return self._cpu_fallback

    def _fallback_consume(self, lines, now_unix) -> List[ConsumeLineResult]:
        """CPU-reference degraded mode.  Note: with device windows enabled
        the fallback counts in the host RegexRateLimitStates, so window
        state diverges from the on-device counters for the duration of the
        outage — under-counting briefly, exactly like the reference
        restarting."""
        self.fallback_batches += 1
        self._note_health()
        return self._fallback_matcher().consume_lines(list(lines), now_unix)

    def _note_health(self) -> None:
        if self._health is None:
            return
        state = self.breaker.state
        if state == CLOSED:
            self._health.ok()
        else:
            self._health.set_status(
                HealthStatus.DEGRADED,
                f"breaker {state}; batches on CPU reference matcher",
            )

    def _gate(self, lines, now, results, use_scratch=True,
              parse_threads=None):
        """Step 1: host parse + allowlist exemption
        (regex_rate_limiter.go:131-172) — one native C pass when available
        (banjax_tpu/native), with the Python reference path per deferred
        line and as fallback.  The gate stays COLUMNAR (workset.py): flag
        masks, unique-string tables, and a per-distinct-(host, ip)
        allowlist check, so no per-line Python objects exist on the hot
        path.  `use_scratch=False` (the pipeline split path) allocates
        fresh parse/dedup buffers: with batches in flight concurrently,
        batch N's work set must not alias buffers batch N+1's parse
        reuses."""
        pre_encoded = None
        nb = None
        if self._native:
            from banjax_tpu import native

            nb = native.parse_encode_batch(
                lines, self.compiled.byte_to_class, self._max_len, now,
                OLD_LINE_CUTOFF_SECONDS,
                scratch=self._parse_scratch if use_scratch else None,
                max_threads=parse_threads,
            )
        if nb is not None:
            work, pre_encoded = self._native_gate(
                nb, lines, now, results, use_scratch=use_scratch
            )
        else:
            lw = ListWork()
            for i, text in enumerate(lines):
                p = parse_line(text, now, OLD_LINE_CUTOFF_SECONDS)
                if p.error:
                    log.warning("could not parse log line: %r", text)
                    results[i].error = True
                    continue
                if p.old_line:
                    results[i].old_line = True
                    continue
                if self.decision_lists.check_is_allowed(p.host, p.ip):
                    results[i].exempted = True
                    continue
                lw.append((i, p))
            work = lw
        return work, pre_encoded

    def _consume_lines_inner(
        self, lines: Sequence[str], now_unix: Optional[float] = None,
        fused_ok: bool = True,
    ) -> List[ConsumeLineResult]:
        now = time.time() if now_unix is None else now_unix
        results = LazyResults(len(lines))

        # 1. host parse + allowlist exemption (see _gate)
        work, pre_encoded = self._gate(lines, now, results)
        if not len(work):
            return results

        # 1b. cold-tier slot admission: refused rows take the classic
        #     per-line host path (matched device-statelessly, windows
        #     applied host-side into the warm tier); admitted rows
        #     continue below with hot-tier slots
        part = self._partition_admission(work, pre_encoded)
        if part is not None:
            work, pre_encoded, work_r, pre_r = part
            self._consume_refused(work_r, pre_r, results)
            if not len(work):
                return results

        # 2a. fully-fused pipeline: match + window apply in ONE device
        #     dispatch (matcher/fused_windows.py) — no dense bitmap ever
        #     crosses the host boundary. Eligible when every rule is
        #     device-decidable and no line in the batch needs host eval.
        if (
            fused_ok
            and self.device_windows is not None
            and self._fw_pipeline is not None
        ):
            if pre_encoded is not None:
                cls_ids, lens, host_eval = pre_encoded
            else:
                cls_ids, lens, host_eval = encode_for_match(
                    self.compiled, [p.rest for _, p in work], self._max_len
                )
                pre_encoded = (cls_ids, lens, host_eval)
            if not host_eval.any():
                self._consume_via_pipeline(work, cls_ids, lens, results)
                return results

        # 2b. device match bitmap for all matchable lines
        bits = self._match_bits(work, pre_encoded)

        # 3a. device window pass: fold the whole batch of match events into
        #     the persistent on-device counters in one step, then replay the
        #     per-event outcomes into results/effectors in reference order
        if self.device_windows is not None:
            self._apply_device_windows(work, bits, results)
            return results

        # 3b. host window pass in original line order
        self._apply_host_windows(work, bits, results)
        return results

    def _apply_host_windows(self, work, bits, results) -> None:
        """Host window pass in original line order: per-site rules for the
        line's host first, then global rules (regex_rate_limiter.go:175-211).
        Lines with no match at all (the overwhelming majority) are
        skipped wholesale; matched lines touch only their matched rule
        ids, in order — O(matches), not O(lines × rules) Python."""
        row_any = bits.any(axis=1)
        for row in np.flatnonzero(row_any):
            i, p = work[int(row)]
            pos = self._rule_pos(p.host)
            ids = np.nonzero(bits[row])[0].tolist()
            try:
                for idx in sorted(
                    (x for x in ids if x in pos), key=pos.__getitem__
                ):
                    _, rule = self._entries[idx]
                    results[i].rule_results.append(
                        self._apply_matched_rule(rule, p)
                    )
            except Exception:  # noqa: BLE001 — a failing effector loses one line, not the batch
                log.exception("error applying rules to log line")
                results[i].error = True

    def close(self) -> None:
        """No buffered state: consume_lines is synchronous per batch."""

    # ---- streaming-pipeline split protocol (pipeline/scheduler.py) ----
    #
    # consume_lines, split at its two natural seams so the scheduler can
    # run the pieces on different stage threads: begin (host parse/gate/
    # encode) → submit (device dispatch, no host sync) → collect (force
    # device→host) → finish (window updates + Banner replay, which the
    # scheduler serializes in admission order).
    #
    # Two device protocols ride the same four calls:
    #
    #   * classic bitmap — _match_bits_submit/collect, dense [B, n_rules]
    #     pulled to host, window apply (device or host) entirely at finish.
    #   * fused two-phase (matcher/fused_windows.py) — when the fused
    #     matcher+windows pipeline is active and the batch has no
    #     host-eval rows, submit dispatches program A (stateless match +
    #     overflow flags) per chunk, any number of batches ahead; the
    #     window commit (program B, state-donated segmented scan) is
    #     DEFERRED to finish, where the drain thread dispatches it
    #     strictly in admission order once each chunk's A-flags resolve.
    #     The dense bitmap never crosses the host boundary — the ~16 MB
    #     per-65k-batch re-upload the classic path pays is gone — and
    #     drain-time staleness composes with the deferred commit as a
    #     tiny per-row live mask.  Overflowing chunks replay classically
    #     mid-pipeline (order turns held until the fallback applies).

    def pipeline_begin(self, lines: Sequence[str], now: float) -> dict:
        """Encode stage: parse + gate + byte-class encode.  Fresh (non-
        scratch) buffers — see _gate — because batches overlap in flight."""
        results = LazyResults(len(lines))
        work, pre_encoded = self._gate(
            lines, now, results, use_scratch=False
        )
        return self._pipeline_state(lines, results, work, pre_encoded)

    def encode_shard(self, lines: Sequence[str], now: float):
        """One row shard of the encode stage: parse + gate + encode over
        a contiguous slice of the admission batch, fresh buffers (shards
        run concurrently on the scheduler's worker pool — the native
        parse and the columnar gate are GIL-free/thread-safe).  Returned
        indices are LOCAL to the shard; pipeline_begin_from_shards
        rebases them."""
        results = LazyResults(len(lines))
        work, pre_encoded = self._gate(
            lines, now, results, use_scratch=False, parse_threads=1
        )
        return work, pre_encoded, results

    def pipeline_begin_from_shards(
        self, lines: Sequence[str], now: float, shards
    ) -> dict:
        """Merge encode_shard outputs back into the exact state
        pipeline_begin would have produced single-threaded.  `shards` is
        [(row0, (work, pre, results)), ...] in row order, covering
        `lines` exactly.  The merge is strict line order end to end:
        results rows rebase by row0, work sets concatenate positionally
        (workset.CompositeWork), the encoded arrays concatenate row-wise,
        and the merged unique-IP table is in global first-appearance
        order — so slot assignment, window events, and ban-log bytes are
        byte-identical to the single-thread path
        (tests/differential/test_host_parallel_differential.py)."""
        results = LazyResults(len(lines))
        parts, offsets, pres = [], [], []
        native_pre = True
        for row0, (work, pre, shard_results) in shards:
            results.absorb(shard_results, row0)
            if not len(work):
                continue
            parts.append(work)
            offsets.append(row0)
            if pre is None:
                native_pre = False
            else:
                pres.append(pre)
        if not parts:
            work, pre_encoded = ListWork(), None
        elif len(parts) == 1 and offsets[0] == 0:
            work = parts[0]
            pre_encoded = pres[0] if (native_pre and pres) else None
        else:
            work = CompositeWork(parts, offsets)
            # a python-parsed shard (no native lib mid-flight) has no
            # encoded arrays: the merged batch re-encodes from rests —
            # correctness first, the fast path needs every shard native
            pre_encoded = None
            if native_pre:
                pre_encoded = tuple(
                    np.concatenate([p[k] for p in pres]) for k in range(3)
                )
        return self._pipeline_state(lines, results, work, pre_encoded)

    def _pipeline_state(self, lines, results, work, pre_encoded) -> dict:
        state = {
            "lines": lines, "results": results, "work": work,
            "pre": pre_encoded, "pend": None, "bits": None,
            "fused": None,  # list of in-flight two-phase chunk entries
        }
        if (
            self._pipeline_fused
            and self._fw_pipeline is not None
            and len(work)
        ):
            if pre_encoded is None:
                pre_encoded = encode_for_match(
                    self.compiled, [p.rest for _, p in work], self._max_len
                )
                state["pre"] = pre_encoded
            if not pre_encoded[2].any():  # no host-eval rows in the batch
                state["fused_eligible"] = True
        return state

    # the scheduler passes its now_fn() into pipeline_submit when this
    # attribute is set — the single-kernel path commits window state at
    # submit, so the staleness live mask is evaluated HERE (deterministic
    # under an injected clock), not at drain
    pipeline_submit_takes_now = True

    def pipeline_submit(self, state: dict, now: Optional[float] = None) -> None:
        if not len(state["work"]):
            return
        part = self._partition_admission(state["work"], state["pre"])
        if part is not None:
            state["work"], state["pre"], work_r, pre_r = part
            # refused rows apply SYNCHRONOUSLY at submit: submits are
            # sequential on the scheduler thread, so this batch's
            # warm-tier writes land before the NEXT batch's admission
            # probe/refill — a refused IP can never race its own state.
            # (Their results ride state["results"] out at finish; the
            # shrunk work keeps host_eval all-false, so fused
            # eligibility computed at begin remains valid.)
            self._consume_refused(work_r, pre_r, state["results"])
            if not len(state["work"]):
                return
        if state.get("fused_eligible") and self._single_kernel_ordered():
            if self._submit_fused_pipeline(state, now):
                return
        state["pend"] = self._match_bits_submit(state["work"], state["pre"])
        if self.device_windows is not None:
            # this batch's window apply happens at ITS drain turn: gate
            # later single-kernel commits (which happen at submit, i.e.
            # EARLIER than this batch's drain) until it completes, or
            # cross-batch window updates would reorder
            with self._drain_window_lock:
                self._drain_window_batches += 1
            state["window_at_drain"] = True

    def _single_kernel_ordered(self) -> bool:
        """Commit-at-submit is only order-safe while no EARLIER admitted
        batch still owes a drain-time window apply (a classic-pend
        fallback from slot refusal or host-eval rows).  While one is
        outstanding, this batch joins the classic path too — the single
        drain thread then applies everything in admission order.  The
        two-program mode commits at drain anyway, so it never gates."""
        fw = self._fw_pipeline
        if fw is None or not fw.single_kernel:
            return True
        with self._drain_window_lock:
            return self._drain_window_batches == 0

    def _submit_fused_pipeline(self, state: dict,
                               now: Optional[float] = None) -> bool:
        """Dispatch the device program(s) for every chunk of the batch.
        Two-program mode dispatches program A (stateless match) per chunk;
        single-kernel mode dispatches the ONE fused match+window program —
        the chunk is final on return, and the 10 s staleness cutoff is
        applied here as the kernel's live-mask input (`now`, from the
        scheduler's clock; falls back to wall time on the direct-call
        path).  Returns False — with every partial entry abandoned — when
        slot allocation refuses, so the caller falls back to the classic
        bitmap protocol for this batch.  Any other failure abandons the
        entries and re-raises (the scheduler then drains the batch
        generically; program A is stateless so nothing double-applies —
        on the single-kernel path an already-committed chunk's generic
        rerun can double-count window hits, never Banner effects)."""
        failpoints.check("matcher.device")
        work = state["work"]
        cls_ids, lens, _ = state["pre"]
        fw = self._fw_pipeline
        sk = fw.single_kernel
        # one fused span replaces the program-a (submit) / program-b
        # (drain) pair: match and window commit are one dispatch now
        span_name = "program-ab-fused" if sk else "program-a"
        if sk and now is None:
            now = time.time()
        entries = []
        try:
            for s in range(0, len(work), self._max_batch):
                wc = work[s : s + self._max_batch]
                live = stale = None
                if sk:
                    ages_s = now - wc.ts_array() / 1e9
                    st = ages_s > OLD_LINE_CUTOFF_SECONDS
                    if st.any():
                        stale, live = st, ~st
                with trace.span(span_name, args={"row0": s}):
                    e = self._submit_pipeline_chunk(
                        wc,
                        cls_ids[s : s + self._max_batch],
                        lens[s : s + self._max_batch],
                        live=live,
                    )
                if e is None:
                    # more distinct IPs than free+unpinned slots (in-flight
                    # batches hold pins until their drains): classic path
                    for prev in entries:
                        self._fw_pipeline.abandon(prev["pend"])
                    return False
                e["row0"] = s
                e["live"] = live
                e["stale"] = stale
                entries.append(e)
        except Exception:
            for prev in entries:
                self._fw_pipeline.abandon(prev["pend"])
            raise
        state["fused"] = entries
        return True

    def pipeline_collect(self, state: dict) -> None:
        if state.get("fused") is not None:
            # wait for every chunk's A-program (compute only — the sparse
            # pull is async and lands before resolve needs it); on failure
            # free the chunks' order turns and pins so the generic-drain
            # rerun cannot deadlock later two-phase batches
            try:
                for e in state["fused"]:
                    buf = e["pend"].sparse_buf
                    try:
                        buf.block_until_ready()
                    except AttributeError:
                        np.asarray(buf)
            except Exception:
                for e in state["fused"]:
                    self._fw_pipeline.abandon(e["pend"])
                state["fused"] = None
                raise
            return
        if state["pend"] is not None:
            state["bits"] = self._match_bits_collect(state["pend"])

    def pipeline_abort(self, state: dict) -> None:
        """Settle a batch the drain stage will never finish (drain-stage
        failure): free the two-phase chunks' order turns and slot pins so
        later batches' resolves can't deadlock.  Idempotent."""
        entries = state.get("fused")
        state["fused"] = None
        self._drain_window_done(state)
        if entries:
            for e in entries:
                try:
                    self._fw_pipeline.abandon(e["pend"])
                except Exception:  # noqa: BLE001 — abort must settle every entry
                    log.exception("fused pipeline abandon failed")

    def _drain_window_done(self, state: dict) -> None:
        """Release one drain-time window-apply slot exactly once per
        batch (pipeline_finish's finally AND pipeline_abort may both
        run for a failing batch)."""
        if state.pop("window_at_drain", False):
            with self._drain_window_lock:
                self._drain_window_batches -= 1

    def pipeline_finish(self, state: dict, now: float):
        """Drain stage: staleness re-check at EFFECTOR DRAIN time (the
        reference's 10 s cutoff, regex_rate_limiter.go:164-167, applied
        end-to-end — a line that aged out while queued in the pipeline is
        dropped here, marked old_line, and counted), then the window pass
        + Banner replay.  Returns (results, n_stale_dropped)."""
        t0 = time.perf_counter()
        results = state["results"]
        work, bits = state["work"], state["bits"]
        n_stale = 0
        try:
            if not len(work):
                return results, 0
            if (
                state.get("fused") is not None
                and self._fw_pipeline.single_kernel
            ):
                # single-kernel chunks committed at submit (live mask =
                # submit-time staleness): the drain is pure event pull +
                # replay, no program-B dispatch, no drain-time re-cut
                n_stale = self._finish_single_kernel(state, results)
                self._note_health()
                return results, n_stale
            ages_s = now - work.ts_array() / 1e9
            stale = ages_s > OLD_LINE_CUTOFF_SECONDS
            if stale.any():
                n_stale = int(stale.sum())
                for k in np.flatnonzero(stale):
                    i, _ = work[int(k)]
                    r = results[i]
                    r.old_line = True
                    r.rule_results = []
            if state.get("fused") is not None:
                self._finish_fused_pipeline(state, stale, results)
                self._note_health()
                return results, n_stale
            if stale.any():
                keep = np.flatnonzero(~stale)
                work = work.take(keep)
                bits = bits[keep]
                if not len(work):
                    return results, n_stale
            if self.device_windows is not None:
                self._apply_device_windows(work, bits, results)
            else:
                self._apply_host_windows(work, bits, results)
            self._note_health()
            return results, n_stale
        finally:
            self._drain_window_done(state)
            self.stats.record_batch(
                len(state["lines"]), time.perf_counter() - t0
            )

    def _finish_fused_pipeline(self, state, stale, results) -> None:
        """Ordered window commit for the two-phase chunks, with depth-
        `drain_resolve_depth` resolve-ahead: up to depth-1 RESOLVED
        chunks stay pending while the next chunk's resolve dispatches its
        window program (B) — so chunk i's event pull/decode/replay runs
        while chunk i+1's B computes on the device, hiding the fixed d2h
        latency the serial drain paid per chunk (ROADMAP PR 3 follow-up).

        Ordering is untouched: resolve order == B dispatch order ==
        device apply order (the pipeline's turn machinery enforces it),
        and replay — hence ban-log byte order — still happens strictly
        chunk-ascending because pending chunks drain before any later
        chunk's fallback/replay emits an effect.  Staleness masks and the
        overflow fallback compose exactly as at depth 1: a stale-masked
        chunk resolves with its live mask, an overflowing chunk first
        drains every pending replay, then replays classically.  A failed
        chunk loses only its own lines — its order turns and pins are
        freed either way (fused_windows' dead-turn sweep), so later
        chunks and later batches keep draining."""
        entries = state["fused"]
        state["fused"] = None
        fw = self._fw_pipeline
        from banjax_tpu.matcher.fused_windows import PipelineOverflow

        depth = self._drain_resolve_depth
        pending: List[dict] = []  # resolved, replay deferred (≤ depth-1)

        def collect_replay(e, overlapped: bool) -> None:
            pend = e["pend"]
            t0 = time.perf_counter()
            # child of the scheduler's ambient `drain` span: event pull +
            # decode + Banner replay for one committed chunk — the work
            # the resolve-ahead hides behind the next chunk's program B
            with trace.span("effector-replay",
                            args={"row0": e["row0"],
                                  "overlapped": overlapped}):
                try:
                    res = fw.collect(pend)
                    self._replay_window_events(
                        e["work"], None,
                        (res.matched_pairs, res.always_bits),
                        res.events, results, live_rows=e["live"],
                    )
                    self.pipelined_fused_chunks += 1
                except Exception:  # noqa: BLE001 — collect released pins/turns in finally
                    log.exception(
                        "pipelined fused event collect failed; chunk lines "
                        "marked error"
                    )
                    self._mark_chunk_error(e, e["chunk_stale"], results)
                    self.note_device_outcome(0.0, ok=False)
                finally:
                    self.stats.note_xfer(pend.h2d_bytes, pend.d2h_bytes)
            if overlapped:
                # the d2h-overlap witness: this collect+replay wall time
                # ran while a later chunk's B was in flight
                ms = (time.perf_counter() - t0) * 1e3
                prev = self.drain_resolve_overlap_ms_ewma
                self.drain_resolve_overlap_ms_ewma = (
                    ms if prev is None else prev + 0.3 * (ms - prev)
                )

        def drain_pending() -> None:
            while pending:
                collect_replay(pending.pop(0), overlapped=False)

        for e in entries:
            pend = e["pend"]
            s = e["row0"]
            n = len(e["work"])
            chunk_stale = stale[s : s + n]
            e["chunk_stale"] = chunk_stale
            live = None
            if chunk_stale.any():
                if chunk_stale.all():
                    # nothing to commit: freeing the turns without a B
                    # dispatch matches the classic path's row removal
                    fw.abandon(pend)
                    continue
                live = ~chunk_stale
            e["live"] = live
            try:
                failpoints.check("matcher.resolve")
                # program B (window commit) dispatch for this chunk, in
                # admission order — child of the ambient `drain` span
                with trace.span("program-b",
                                args={"row0": s,
                                      "masked": live is not None}):
                    fw.resolve(pend, live=live)
            except PipelineOverflow as ov:
                # earlier chunks' effects must fire before this chunk's
                # classic replay: drain the resolve-ahead window first
                drain_pending()
                trace.instant("fused-overflow-fallback", {"row0": s})
                self.pipelined_fused_fallbacks += 1
                try:
                    self._pipeline_fallback_entry(e, ov, results, live=live)
                except Exception:  # noqa: BLE001 — one chunk's loss, not the stream's
                    log.exception(
                        "pipelined fused overflow fallback failed; chunk "
                        "lines marked error"
                    )
                    self._mark_chunk_error(e, chunk_stale, results)
                    self.note_device_outcome(0.0, ok=False)
                self.stats.note_xfer(pend.h2d_bytes, pend.d2h_bytes)
                continue
            except Exception:  # noqa: BLE001 — resolve frees turns/pins on its own errors
                # an abort BEFORE resolve (the matcher.resolve failpoint)
                # leaves the chunk submitted: settle its turns/pins here
                # so the dead-turn sweep keeps later drains alive
                if pend.state == "submitted":
                    fw.abandon(pend)
                drain_pending()
                log.exception(
                    "pipelined fused window commit failed; chunk lines "
                    "marked error"
                )
                self._mark_chunk_error(e, chunk_stale, results)
                self.note_device_outcome(0.0, ok=False)
                continue
            pending.append(e)
            while len(pending) > depth - 1:
                head = pending.pop(0)
                collect_replay(head, overlapped=bool(pending))
        drain_pending()

    def _finish_single_kernel(self, state, results) -> int:
        """Ordered drain for single-kernel chunks: the window commit
        already ran in-kernel at submit (the live mask carried the
        submit-time 10 s staleness cut), so each chunk's drain is a pure
        d2h pull (async since submit) + decode + Banner replay —
        `drain_resolve_depth` is a no-op here because there is no
        program-B dispatch left to overlap.  Overflow / chain-gated
        chunks replay classically in chunk order via the existing
        fallback (their kernel committed nothing — the in-kernel gate)."""
        from banjax_tpu.matcher.fused_windows import PipelineOverflow

        entries = state["fused"]
        state["fused"] = None
        fw = self._fw_pipeline
        n_stale = 0
        for e in entries:
            stale = e.get("stale")
            live = e.get("live")
            if stale is not None:
                n_stale += int(stale.sum())
                for k in np.flatnonzero(stale):
                    i, _ = e["work"][int(k)]
                    r = results[i]
                    r.old_line = True
                    r.rule_results = []
            chunk_stale = (
                stale if stale is not None
                else np.zeros(len(e["work"]), dtype=bool)
            )
            e["chunk_stale"] = chunk_stale
            pend = e["pend"]
            try:
                failpoints.check("matcher.resolve")
                fw.resolve(pend)
            except PipelineOverflow as ov:
                trace.instant("fused-overflow-fallback", {"row0": e["row0"]})
                self.pipelined_fused_fallbacks += 1
                try:
                    self._pipeline_fallback_entry(e, ov, results, live=live)
                except Exception:  # noqa: BLE001 — one chunk's loss, not the stream's
                    log.exception(
                        "single-kernel overflow fallback failed; chunk "
                        "lines marked error"
                    )
                    self._mark_chunk_error(e, chunk_stale, results)
                    self.note_device_outcome(0.0, ok=False)
                self.stats.note_xfer(pend.h2d_bytes, pend.d2h_bytes)
                continue
            except Exception:  # noqa: BLE001 — a dead chunk must not wedge the drain
                if pend.state == "submitted":
                    fw.abandon(pend)
                log.exception(
                    "single-kernel event pull failed; chunk lines marked "
                    "error"
                )
                self._mark_chunk_error(e, chunk_stale, results)
                self.note_device_outcome(0.0, ok=False)
                continue
            with trace.span("effector-replay", args={"row0": e["row0"]}):
                try:
                    res = fw.collect(pend)
                    self._replay_window_events(
                        e["work"], None,
                        (res.matched_pairs, res.always_bits),
                        res.events, results, live_rows=live,
                    )
                    self.pipelined_fused_chunks += 1
                except Exception:  # noqa: BLE001 — collect settled pins/turns in finally
                    log.exception(
                        "single-kernel event collect failed; chunk lines "
                        "marked error"
                    )
                    self._mark_chunk_error(e, chunk_stale, results)
                    self.note_device_outcome(0.0, ok=False)
                finally:
                    self.stats.note_xfer(pend.h2d_bytes, pend.d2h_bytes)
        return n_stale

    def _mark_chunk_error(self, e, chunk_stale, results) -> None:
        for k in np.flatnonzero(~chunk_stale):
            i, _ = e["work"][int(k)]
            results[i].error = True

    def probe(self, now_unix: Optional[float] = None) -> bool:
        """Synthetic device probe (ROADMAP matcher-staleness item): one
        canned line through the pure match path — no window updates, no
        Banner effects — so a wedged device trips the breaker/health while
        the tailer is idle, not at the next traffic burst.  Returns False
        when the probe failed or the breaker refused it."""
        if not self.breaker.allow():
            return False
        now = time.time() if now_unix is None else now_unix
        line = (
            f"{now:.6f} 203.0.113.1 GET banjax-probe.invalid "
            "GET /__banjax_probe HTTP/1.1 probe -"
        )
        t0 = time.perf_counter()
        try:
            lw = ListWork()
            lw.append((0, parse_line(line, now, OLD_LINE_CUTOFF_SECONDS)))
            self._match_bits(lw, None)
        except Exception:  # noqa: BLE001 — a probe failure is the signal, not a crash
            log.exception("matcher device probe failed")
            self.note_device_outcome(time.perf_counter() - t0, ok=False)
            return False
        self.note_device_outcome(time.perf_counter() - t0, ok=True)
        return self.breaker.state == CLOSED

    def _slots_for_work(self, work) -> Optional[np.ndarray]:
        """Window-slot ids for a work batch: one LRU decision + one pin
        per DISTINCT ip (the unique tables the gate already built), then a
        gather back to row order. Pin/release semantics are unchanged —
        release_pins deduplicates slot ids either way."""
        uips, uinv = work.unique_ips()
        uslots = self.device_windows.slots_for_unique_ips(uips)
        if uslots is None:
            return None
        if self.traffic_sketch is not None:
            # refresh the sketch's slot→ip-hash table for this batch's
            # distinct assignments (scatters only CHANGED slots); a
            # telemetry failure must never cost the batch
            try:
                self.traffic_sketch.note_assignments(uips, uslots)
            except Exception:  # noqa: BLE001 — sketch is passive by contract
                log.exception("traffic sketch slot-table refresh failed")
        return uslots[uinv]

    # ---- cold-tier slot admission (mega-state tiering) ----

    def _partition_admission(self, work, pre_encoded):
        """Split one batch at the slot-admission gate.  Returns None when
        admission is off or every row admitted; else
        (work_admitted, pre_admitted, work_refused, pre_refused) —
        row-disjoint takes of the batch, partitioned per DISTINCT ip so
        all of an IP's rows land on one side (per-IP event order is
        therefore untouched; only cross-IP interleaving can differ from
        the ungated engine).

        The gate admits on `estimate + this batch's row count`, so an IP
        whose cumulative rows reach the threshold is admitted in THAT
        batch: a refused IP has strictly fewer than min_estimate total
        rows behind it — the bounded-ban-delay invariant the
        differential suite asserts.  Refused counts then fold into the
        sketch's exact host mirror so the next batch's estimate sees
        them.  Any gate failure admits the whole batch (fail open)."""
        if (
            not self._slot_admission
            or self.device_windows is None
            or self.traffic_sketch is None
            or not len(work)
        ):
            return None
        try:
            uips, uinv = work.unique_ips()
            counts = np.bincount(uinv, minlength=len(uips)).astype(np.int64)
            sk = self.traffic_sketch
            hashes = sk.base_hashes(uips)
            est = sk.estimate_ips(uips, hashes=hashes) + counts
            mask_u = self.device_windows.admission_mask(
                uips,
                estimates=est,
                min_estimate=self._admission_min_estimate,
                counts=counts,
            )
            if mask_u.all():
                return None
            refused_u = np.flatnonzero(~mask_u)
            sk.fold_refused(
                [uips[int(i)] for i in refused_u],
                counts[refused_u],
                hashes=hashes[refused_u],
            )
            row_mask = mask_u[uinv]
            adm = np.flatnonzero(row_mask)
            ref = np.flatnonzero(~row_mask)
            work_a, work_r = work.take(adm), work.take(ref)
            pre_a = pre_r = None
            if pre_encoded is not None:
                cls_ids, lens, host_eval = pre_encoded
                pre_a = (cls_ids[adm], lens[adm], host_eval[adm])
                pre_r = (cls_ids[ref], lens[ref], host_eval[ref])
            return work_a, pre_a, work_r, pre_r
        except Exception:  # noqa: BLE001 — the gate is an optimization; fail open
            log.exception("slot-admission gate failed; admitting batch")
            return None

    def _consume_refused(self, work, pre_encoded, results) -> None:
        """Classic per-line path for slot-REFUSED rows: device-STATELESS
        match (no slot claimed, no device window state touched), then the
        window transitions applied host-side in the canonical
        (line, rule_id) order — apply_host_events replicates _window_step
        exactly and homes the state in the warm tier, so a refused IP
        that matched anything is admitted next batch.  Effects replay
        through the same _replay_window_events as every other path
        (Banner, provenance, rule pressure — full parity)."""
        if not len(work):
            return
        bits = self._match_bits(work, pre_encoded)
        events_in = []
        row_any = bits.any(axis=1)
        for row in np.flatnonzero(row_any):
            row = int(row)
            _, p = work[row]
            pos = self._rule_pos(p.host)
            # applicable rule ids ascending == per-site-then-global
            # (per-site ids precede global ids in self._entries)
            for idx in sorted(
                x for x in np.nonzero(bits[row])[0].tolist() if x in pos
            ):
                _, rule = self._entries[idx]
                if rule.hosts_to_skip.get(p.host):
                    continue  # no window event — active_table parity
                events_in.append((row, idx, p.ip, p.timestamp_ns))
        events = self.device_windows.apply_host_events(events_in)
        self._replay_window_events(work, bits, None, events, results)

    def _native_gate(self, nb, lines, now, results, use_scratch=True):
        """Vectorized step 1 over a native ParsedBatch: flag masks, unique
        ip/host tables (workset.unique_spans), allowlist per DISTINCT
        (host, ip) with a snapshot-keyed cache, and a columnar NativeWork.
        Semantics identical to the per-line reference loop; cost is
        O(distinct strings + matched rows), not O(lines)."""
        from banjax_tpu import native

        dedup_scratch = self._dedup_scratch if use_scratch else None

        n = nb.n
        flags = np.asarray(nb.flags[:n])
        err = (flags & native.FLAG_ERROR) != 0
        old = (flags & native.FLAG_OLD) != 0
        ts = nb.ts_ns[:n].astype(np.int64, copy=True)

        defer_map: Dict[int, ParsedLine] = {}
        for r in np.flatnonzero(flags & native.FLAG_DEFER):
            r = int(r)
            p = parse_line(lines[r], now, OLD_LINE_CUTOFF_SECONDS)
            defer_map[r] = p
            err[r] = p.error
            old[r] = p.old_line
            if not p.error:
                # Python float()*1e9 can exceed int64 (the columnar array
                # feeding the device windows); clamp HERE only — replay and
                # the host window path read the exact Python int from the
                # deferred ParsedLine itself
                ts[r] = min(max(p.timestamp_ns, -(2**63)), 2**63 - 1)

        for r in np.flatnonzero(err):
            log.warning("could not parse log line: %r", lines[int(r)])
            results[int(r)].error = True
        for r in np.flatnonzero(old & ~err):
            results[int(r)].old_line = True

        cand = np.flatnonzero(~err & ~old)
        if cand.size == 0:
            return ListWork(), None

        # distinct ip/host string tables over the candidate rows; deferred
        # rows have no blob spans — patch their strings in via the tables
        dset = set(defer_map)
        vrows = np.asarray(
            [r for r in cand if int(r) not in dset], dtype=np.int64
        ) if dset else cand
        text = nb.text()
        ips_u, ip_inv_v = unique_spans(
            nb.ip_off[vrows], nb.ip_len[vrows],
            lambda k: nb.ip(int(vrows[k])),
            blob=nb.blob, text=text, dedup_scratch=dedup_scratch,
        )
        hosts_u, host_inv_v = unique_spans(
            nb.host_off[vrows], nb.host_len[vrows],
            lambda k: nb.host(int(vrows[k])),
            blob=nb.blob, text=text, dedup_scratch=dedup_scratch,
        )
        ip_inv = np.empty(cand.size, dtype=np.int64)
        host_inv = np.empty(cand.size, dtype=np.int64)
        if dset:
            # vectorized membership/positions (cand is sorted): a python
            # per-element loop here would cost O(lines) whenever ANY row
            # deferred
            # sorted so deferred rows append to the unique tables in LINE
            # order (first-appearance contract), not set hash order
            darr = np.sort(np.fromiter(dset, dtype=np.int64))
            vmask = ~np.isin(cand, darr)
            ip_inv[vmask] = ip_inv_v
            host_inv[vmask] = host_inv_v
            iidx = {s: j for j, s in enumerate(ips_u)}
            hidx = {s: j for j, s in enumerate(hosts_u)}
            for r in darr.tolist():
                p = defer_map[r]
                # position of r in cand, or absent (errored/old defer rows)
                k = int(np.searchsorted(cand, r))
                if k >= cand.size or cand[k] != r:
                    continue
                j = iidx.get(p.ip)
                if j is None:
                    j = len(ips_u)
                    ips_u.append(p.ip)
                    iidx[p.ip] = j
                ip_inv[k] = j
                j = hidx.get(p.host)
                if j is None:
                    j = len(hosts_u)
                    hosts_u.append(p.host)
                    hidx[p.host] = j
                host_inv[k] = j
        else:
            ip_inv[:] = ip_inv_v
            host_inv[:] = host_inv_v

        # allowlist per distinct (host, ip) pair, cached across batches
        # until the static-lists generation bumps (hot reload) — the CIDR
        # filters parse the ip string per check, which at per-line rates
        # costs more than the device match. A decision-lists object
        # WITHOUT the public counter never caches (fail safe, not stale).
        gen = getattr(self.decision_lists, "generation", None)
        if gen is None:
            self._allow_cache = {}
            self._allow_cache_snap = None
        elif gen != self._allow_cache_snap or \
                len(self._allow_cache) > 500_000:
            self._allow_cache = {}
            self._allow_cache_snap = gen
        has_allow = getattr(
            self.decision_lists, "has_any_allow_entries", lambda: True
        )()
        if has_allow:
            n_ip = max(1, len(ips_u))
            pair = host_inv * n_ip + ip_inv
            upair, upair_inv = np.unique(pair, return_inverse=True)
            allowed_u = np.empty(upair.size, dtype=bool)
            cache = self._allow_cache
            check = self.decision_lists.check_is_allowed
            for j, pr in enumerate(upair.tolist()):
                h = hosts_u[pr // n_ip]
                ip = ips_u[pr % n_ip]
                v = cache.get((h, ip))
                if v is None:
                    v = check(h, ip)
                    cache[(h, ip)] = v
                allowed_u[j] = v
            allowed = allowed_u[upair_inv]
            for k in np.flatnonzero(allowed):
                results[int(cand[k])].exempted = True
            keep = ~allowed
            rows = cand[keep]
        else:
            # no allow entries anywhere: nothing can be exempted
            keep = slice(None)
            rows = cand
        if rows.size == 0:
            return ListWork(), None
        work = NativeWork(
            nb, rows, ips_u, ip_inv[keep], hosts_u, host_inv[keep],
            ts[rows], defer_map,
        )

        deferred = (flags[rows] & native.FLAG_DEFER) != 0
        if rows.size == n:
            # nothing filtered (the common clean-traffic batch): views,
            # not 33 MB gather copies of the class matrix
            cls_ids = nb.cls_ids[:n]
            lens = nb.lens[:n]
        else:
            cls_ids = nb.cls_ids[rows]
            lens = nb.lens[rows]
        host_eval = (flags[rows] & native.FLAG_HOST_EVAL) != 0
        if deferred.any():
            # deferred rows were Python-parsed: encode them the Python way
            # into the same arrays
            d_idx = np.flatnonzero(deferred)
            d_cls, d_lens, d_he = encode_for_match(
                self.compiled,
                [work[int(k)][1].rest for k in d_idx],
                self._max_len,
            )
            cls_ids[d_idx] = d_cls
            lens[d_idx] = d_lens
            host_eval[d_idx] = d_he
        return work, (cls_ids, lens, host_eval)

    def _with_window_slots(self, work, split, apply_fn, results) -> None:
        """Shared scaffolding for every device-windows consume path: slot
        allocation with recursive batch split when it refuses, per-line
        ts/host prep, and the pin-lifecycle contract. `apply_fn(work,
        slots, ts_s, ts_ns, host_idx, results)` OWNS the pins from the
        moment it is entered and must release them exactly once on every
        path; any failure before that hand-off releases them here.
        `split(lo, hi)` returns the work-aligned payload slices for a
        recursive half-batch."""
        from banjax_tpu.matcher.windows import split_ns

        dw = self.device_windows
        slots = self._slots_for_work(work)
        if slots is None:
            if len(work) <= 1:
                log.error(
                    "device-windows slot allocation failed for a single "
                    "line (capacity=%d, all slots pinned); dropping line",
                    dw.capacity,
                )
                for i, _ in work:
                    results[i].error = True
                return
            mid = max(1, len(work) // 2)
            self._with_window_slots(work[:mid], *split(0, mid), results)
            self._with_window_slots(
                work[mid:], *split(mid, len(work)), results
            )
            return
        handed_off = False
        try:
            ts_s, ts_ns = split_ns(work.ts_array())
            host_idx = work.host_idx(self._host_row)
            handed_off = True
            apply_fn(work, slots, ts_s, ts_ns, host_idx, results)
        except Exception:
            if not handed_off:
                dw.release_pins(slots)
            raise

    def _consume_via_pipeline(self, work, cls_ids, lens, results) -> None:
        """Two-program fused path (matcher/fused_windows.py): program A
        (stateless match + overflow flags) dispatches ahead; program B
        (window apply) dispatches strictly in chunk order once each
        chunk's flags resolve ok. Up to two chunks overlap: chunk N's
        device→host pulls hide behind chunk N+1's match compute, and the
        apply order — hence the reference's log order — is never violated,
        even across overflow fallbacks (an overflowing chunk drains all
        earlier chunks first, then replays classically before any later
        apply dispatches)."""
        failpoints.check("matcher.device")
        from banjax_tpu.matcher.fused_windows import PipelineOverflow

        chunks = [
            (work[s : s + self._max_batch],
             cls_ids[s : s + self._max_batch],
             lens[s : s + self._max_batch])
            for s in range(0, max(1, len(work)), self._max_batch)
        ]
        q: List[dict] = []  # in-flight entries, oldest first

        def collect_replay(e):
            res = self._fw_pipeline.collect(e["pend"])
            sparse = (res.matched_pairs, res.always_bits)
            self._replay_window_events(
                e["work"], None, sparse, res.events, results
            )

        def resolve_entry(e):
            """Resolve e (dispatching its B apply); on overflow, drain
            every earlier chunk first, then replay e classically. Returns
            False when e was consumed by the fallback."""
            try:
                self._fw_pipeline.resolve(e["pend"])
                return True
            except PipelineOverflow as ov:
                drained = False
                try:
                    while q and q[0] is not e:
                        collect_replay(q.pop(0))
                    drained = True
                finally:
                    if not drained:
                        # the drain itself failed: free e's pins and order
                        # turns so the error can't become a deadlock
                        self.device_windows.release_pins(e["slots"])
                        self._fw_pipeline.fallback_done(e["pend"])
                        if q and q[0] is e:
                            q.pop(0)
                if q and q[0] is e:
                    q.pop(0)
                self._pipeline_fallback_entry(e, ov, results)
                return False

        def drain_all():
            while q:
                if q[-1]["pend"].state == "submitted":
                    if not resolve_entry(q[-1]):
                        continue
                head = q.pop(0)
                if head["pend"].state in ("failed", "done"):
                    continue  # error/fallback paths already settled it
                collect_replay(head)

        try:
            for wc, cc, lc in chunks:
                entry = self._submit_pipeline_chunk(wc, cc, lc)
                if entry is None:
                    # slot allocation refused (more distinct IPs than
                    # free+unpinned slots): drain in-flight pins, then run
                    # this chunk through the splitting sync path
                    drain_all()
                    self._pipeline_chunk_sync(wc, cc, lc, results)
                    continue
                q.append(entry)
                if len(q) >= 2 and q[-2]["pend"].state == "submitted":
                    # resolve the previous chunk → its B apply dispatches
                    # while THIS chunk's match computes
                    resolve_entry(q[-2])
                if len(q) >= 3:
                    collect_replay(q.pop(0))
            drain_all()
        except Exception:
            # failures mid-burst: drain what we can so pins and the
            # pipeline's order turns are not leaked for in-flight chunks
            try:
                drain_all()
            except Exception:  # noqa: BLE001 — first error wins
                log.exception("pipeline drain after failure also failed")
            raise

    def _submit_pipeline_chunk(self, work, cls_ids, lens, live=None):
        """Allocate slots + dispatch the chunk's device program (A, or
        the single fused kernel — `live` is its commit mask); None when
        slot allocation refuses. Pins transfer to the pipeline on
        success."""
        from banjax_tpu.matcher.windows import split_ns

        dw = self.device_windows
        slots = self._slots_for_work(work)
        if slots is None:
            return None
        try:
            ts_s, ts_ns = split_ns(work.ts_array())
            host_idx = work.host_idx(self._host_row)
            pend = self._fw_pipeline.submit(
                cls_ids, lens, slots, ts_s, ts_ns, host_idx, live=live
            )
        except Exception:
            dw.release_pins(slots)
            raise
        return {
            "work": work, "cls": cls_ids, "lens": lens, "slots": slots,
            "ts_s": ts_s, "ts_ns": ts_ns, "host_idx": host_idx,
            "pend": pend,
        }

    def _pipeline_chunk_sync(self, work, cls_ids, lens, results) -> None:
        """Non-overlapped fallback for a chunk whose slot allocation
        refused even with nothing in flight: the shared splitting
        scaffolding recursively halves until allocations fit, running each
        piece submit→collect serially."""
        from banjax_tpu.matcher.fused_windows import PipelineOverflow

        def make(cls_c, lens_c):
            def apply_fn(work_c, slots, ts_s, ts_ns, host_idx, results_c):
                dw = self.device_windows
                try:
                    pend = self._fw_pipeline.submit(
                        cls_c, lens_c, slots, ts_s, ts_ns, host_idx
                    )
                except Exception:
                    dw.release_pins(slots)
                    raise
                e = {
                    "work": work_c, "cls": cls_c, "lens": lens_c,
                    "slots": slots, "ts_s": ts_s, "ts_ns": ts_ns,
                    "host_idx": host_idx, "pend": pend,
                }
                try:
                    res = self._fw_pipeline.collect(pend)
                except PipelineOverflow as ov:
                    self._pipeline_fallback_entry(e, ov, results_c)
                    return
                sparse = (res.matched_pairs, res.always_bits)
                self._replay_window_events(
                    work_c, None, sparse, res.events, results_c
                )

            def split(lo, hi):
                return make(cls_c[lo:hi], lens_c[lo:hi])

            return split, apply_fn

        self._with_window_slots(work, *make(cls_ids, lens), results)

    def _pipeline_fallback_entry(self, e, ov, results, live=None) -> None:
        """Classic replay of one overflowing chunk (shared by the sync and
        overlapped paths; caller guarantees all earlier chunks applied).
        `live` (bool [n] or None) masks drain-stale rows out of both the
        window apply and the replay — the streaming pipeline's staleness
        drop carried through the fallback."""
        dw = self.device_windows
        pend = e["pend"]
        n = len(e["work"])
        try:
            if ov.candidate_overflow:
                # stage 2 never saw the excess lines: recompute full-NFA
                bits = self._single_stage_bits(
                    n, e["cls"], e["lens"], np.zeros(n, dtype=bool),
                    np.arange(n),
                )
                if live is not None:
                    bits = bits * live[:, None].astype(np.uint8)
                apply_bits = bits
            else:
                # bitmap is complete: keep it DEVICE-resident for the
                # apply (re-uploading ~16 MB is the transfer this module
                # exists to avoid); replay uses the sparse rows decoded at
                # resolve when they fit, else one pull
                apply_bits = pend.bits_dev[:n]
                if live is not None:
                    apply_bits = apply_bits * jnp.asarray(
                        live.astype(np.uint8)
                    )[:, None]
                bits = None
        except Exception:
            dw.release_pins(e["slots"])
            self._fw_pipeline.fallback_done(pend)
            raise
        try:
            events = dw.apply_bitmap(  # releases the pins itself
                apply_bits, e["slots"], e["ts_s"], e["ts_ns"],
                self._active_table, e["host_idx"],
            )
        finally:
            self._fw_pipeline.fallback_done(pend)
        if bits is None and pend.matched_pairs is not None:
            sparse = (pend.matched_pairs, pend.always_bits)
            self._replay_window_events(
                e["work"], None, sparse, events, results, live_rows=live
            )
            return
        if bits is None:
            bits = np.asarray(pend.bits_dev)[:n]
            if live is not None:
                bits = bits * live[:, None].astype(np.uint8)
        self._replay_window_events(e["work"], bits, None, events, results)

    def _sparse_row_sets(self, n, sparse):
        """Per-row matched rule-id sets from the pipeline's sparse result
        ((row, rule) pairs: caller_row * R8 + packed stage-2 bit column)."""
        matched_pairs, always_bits = sparse
        plan = self._prefilter.plan
        row_ids: Dict[int, set] = {}
        if matched_pairs is not None and len(matched_pairs):
            R8 = self._prefilter._nf8 * 8
            rows_idx, cols = matched_pairs // R8, matched_pairs % R8
            ok = cols < plan.stage2.n_rules
            for row, rid in zip(rows_idx[ok], plan.f_idx[cols[ok]]):
                row_ids.setdefault(int(row), set()).add(int(rid))
        if always_bits is not None and plan.n_always:
            ab = np.unpackbits(
                always_bits[:n], axis=1, count=plan.n_always
            )
            for row, col in zip(*np.nonzero(ab)):
                row_ids.setdefault(int(row), set()).add(
                    int(plan.a_idx[col])
                )
        return row_ids

    def _replay_window_events(
        self, work, bits, sparse, events, results, live_rows=None
    ) -> None:
        """Replay window events + match bookkeeping into ConsumeLineResults
        (per-site-then-global rule order, Banner per exceeded event) —
        shared by the classic bitmap path and the fused pipeline.
        `live_rows` (bool [n]) skips rows the drain-time staleness check
        dropped: their bits were masked out of the window apply, so no
        event exists for them and no effect may fire."""
        evmap = {(e.line, e.rule_id): e for e in events}
        if self.traffic_sketch is not None and events:
            # per-rule match pressure, counted where every fired window
            # event already lands (fused commit, overflow fallback and
            # classic apply all replay through here) — exact even when a
            # chunk's device bitmap overflowed
            try:
                self.traffic_sketch.note_rule_events(
                    e.rule_id for e in events
                )
            except Exception:  # noqa: BLE001 — sketch is passive
                log.exception("traffic sketch rule-pressure update failed")
        if sparse is not None:
            row_ids = self._sparse_row_sets(len(work), sparse)
            row_iter = sorted(row_ids)
        else:
            row_any = bits.any(axis=1)
            row_iter = (r for r in range(len(work)) if row_any[r])
        if live_rows is not None:
            row_iter = (r for r in row_iter if live_rows[r])
        for row in row_iter:
            i, p = work[row]
            # per-site-then-global ORDER via a position dict over the few
            # matched ids — scanning the full rule-order array per row is
            # O(n_rules) and dominated the replay at 1k-rule scale
            pos = self._rule_pos(p.host)
            if sparse is not None:
                ids = row_ids[row]
            else:
                ids = np.nonzero(bits[row])[0].tolist()
            matched = sorted(
                (x for x in ids if x in pos), key=pos.__getitem__
            )
            try:
                for idx in matched:
                    _, rule = self._entries[idx]
                    result = RuleResult(rule_name=rule.rule, regex_match=True)
                    if rule.hosts_to_skip.get(p.host):
                        result.skip_host = True
                        results[i].rule_results.append(result)
                        continue
                    result.skip_host = False
                    e = evmap[(row, idx)]
                    result.seen_ip = e.seen_ip
                    result.rate_limit_result = RateLimitResult(
                        match_type=e.match_type, exceeded=e.exceeded
                    )
                    if e.exceeded:
                        self.banner.ban_or_challenge_ip(
                            self.config, p.ip, rule.decision, p.host
                        )
                        self.banner.log_regex_ban(
                            self.config, p.timestamp_ns / 1e9, p.ip,
                            rule.rule, p.rest, rule.decision,
                        )
                        # fixed-window semantics: the ban fires the hit
                        # after the threshold; the ambient drain span
                        # supplies the admitting batch's trace id
                        provenance.record(
                            provenance.SOURCE_RATE_LIMIT, p.ip,
                            rule.decision, rule=rule.rule, rule_index=idx,
                            hits=rule.hits_per_interval + 1,
                        )
                    results[i].rule_results.append(result)
            except Exception:  # noqa: BLE001 — a failing effector loses one line, not the batch
                log.exception("error applying rules to log line")
                results[i].error = True

    def _apply_device_windows(self, work, bits, results) -> None:
        """Classic device window path: apply_bitmap per batch, then replay
        (shared scaffolding handles slot allocation/split/pin lifecycle)."""

        def make(bits_c):
            def apply_fn(work_c, slots, ts_s, ts_ns, host_idx, results_c):
                # the dense-bitmap re-upload the fused two-phase path
                # exists to eliminate: count it so the win is measurable
                if isinstance(bits_c, np.ndarray):
                    self.stats.note_xfer(h2d_bytes=bits_c.nbytes)
                if self.traffic_sketch is not None:
                    # fold the chunk into the count-min/HLL sketches (the
                    # fused paths do this at their device submit instead)
                    try:
                        self.traffic_sketch.update(slots, len(work_c))
                    except Exception:  # noqa: BLE001 — sketch is passive
                        log.exception("traffic sketch update failed")
                events = self.device_windows.apply_bitmap(
                    bits_c, slots, ts_s, ts_ns, self._active_table, host_idx
                )
                self._replay_window_events(
                    work_c, bits_c, None, events, results_c
                )

            def split(lo, hi):
                return make(bits_c[lo:hi])

            return split, apply_fn

        self._with_window_slots(work, *make(bits), results)

    # ---- internals ----

    def _match_bits(self, work, pre_encoded=None) -> np.ndarray:
        """[N, n_rules] uint8 — exact regex-match bitmap for each line of
        a work batch ((index, line) sequence).

        `pre_encoded` = (cls_ids, lens, host_eval) from the native parse
        pass; when given, the Python re-encode is skipped AND line rests
        materialize only for host-fallback rows. The fused prefilter
        consumes it directly — its plan is built against THIS matcher's
        byte classes (build_plan byte_classes=...), so the one encode
        feeds stage 1, stage 2, and the single-stage fallback.

        Split into submit (device dispatch, no host sync) and collect
        (force device→host + host fallbacks) so the streaming pipeline
        scheduler can hide batch N's pull behind batch N+1's compute."""
        return self._match_bits_collect(
            self._match_bits_submit(work, pre_encoded)
        )

    def _match_bits_submit(self, work, pre_encoded=None) -> dict:
        """Dispatch the device match for a work batch without forcing any
        device→host transfer; `_match_bits_collect` completes it."""
        failpoints.check("matcher.device")
        n = len(work)
        rests = (
            None if pre_encoded is not None
            else [p.rest for _, p in work]
        )
        cls_ids, lens, host_eval = pre_encoded or encode_for_match(
            self.compiled, rests, self._max_len
        )
        device_rows = np.flatnonzero(~host_eval)
        pend = {
            "n": n, "work": work, "rests": rests, "cls": cls_ids,
            "lens": lens, "host_eval": host_eval, "device_rows": device_rows,
        }
        if self._prefilter is not None:
            # host_eval rows are decided by host `re` in collect; zeroing
            # their length keeps them out of the device bitmap w/o a gather
            dev_lens = np.where(host_eval, 0, lens)
            # submit every chunk before collecting any: each chunk's
            # device→host pull (fixed ~65 ms tunnel latency) overlaps
            # the next chunk's compute
            pend["kind"] = "prefilter"
            pend["chunks"] = [
                (sl, self._prefilter.submit(cls_ids[sl], dev_lens[sl]))
                for sl in (
                    slice(s, min(n, s + self._max_batch))
                    for s in range(0, n, self._max_batch)
                )
            ]
        elif self._mesh_matcher is not None:
            # sharded submit: dispatch the mesh device step per chunk
            # without forcing any device→host pull — collect merges the
            # per-shard results back into line order, so the pipeline
            # overlaps a sharded batch exactly like a single-device one
            pend["kind"] = "mesh"
            pend["chunks"] = [
                (rows, self._mesh_matcher.submit(cls_ids[rows], lens[rows]))
                for rows in (
                    device_rows[s : s + self._max_batch]
                    for s in range(0, len(device_rows), self._max_batch)
                )
            ]
        else:
            pend["kind"] = "single"
            pend["chunks"] = self._single_stage_submit(
                cls_ids, lens, device_rows
            )
        return pend

    def _match_bits_collect(self, pend: dict) -> np.ndarray:
        """Force the submitted match to a host [N, n_rules] bitmap and run
        the host fallback passes (over-length lines; unlowerable rules)."""
        n = pend["n"]
        work, rests = pend["work"], pend["rests"]
        cls_ids, lens = pend["cls"], pend["lens"]
        host_eval, device_rows = pend["host_eval"], pend["device_rows"]

        def rest_of(row: int) -> str:
            return work[row][1].rest if rests is None else rests[row]

        if pend["kind"] == "prefilter":
            from banjax_tpu.matcher.prefilter import PrefilterOverflow

            try:
                bits = np.zeros((n, self.compiled.n_rules), dtype=np.uint8)
                for sl, p in pend["chunks"]:
                    bits[sl] = self._prefilter.collect(p)
                    self.stats.note_xfer(
                        getattr(p, "h2d_bytes", 0), getattr(p, "d2h_bytes", 0)
                    )
                # a zero-length row must contribute NO device bits (the
                # empty_only always-rule reconstruction keys on lens == 0,
                # which is also how host_eval rows were masked out)
                bits[host_eval] = 0
            except PrefilterOverflow as e:
                # adversarial all-matching traffic: rerun single-stage (the
                # full-NFA path has no candidate capacity to overflow)
                log.info("prefilter overflow (%s); batch reruns single-stage", e)
                bits = self._single_stage_bits(
                    n, cls_ids, lens, host_eval, device_rows
                )
        elif pend["kind"] == "mesh":
            bits = np.zeros((n, self.compiled.n_rules), dtype=np.uint8)
            for rows, p in pend["chunks"]:
                bits[rows] = self._mesh_matcher.collect(p)
                self.stats.note_xfer(
                    p.get("h2d_bytes", 0), p.get("d2h_bytes", 0)
                )
        else:
            bits = self._single_stage_collect(n, pend["chunks"])

        # host fallback: whole lines the device can't decide
        for row in np.flatnonzero(host_eval):
            rest = rest_of(int(row))
            for idx, (_, rule) in enumerate(self._entries):
                if rule.regex.search(rest) is not None:
                    bits[row, idx] = 1
        # host fallback: rules the compiler couldn't lower
        for idx in self._host_rule_idx:
            rule = self._entries[idx][1]
            for row in device_rows:
                if rule.regex.search(rest_of(int(row))) is not None:
                    bits[row, idx] = 1
        return bits

    def _single_stage_submit(self, cls_ids, lens, device_rows) -> list:
        """Dispatch the full-NFA match per max_batch chunk; the returned
        device arrays are NOT forced — collect does that, so a caller can
        overlap this batch's pull with the next batch's compute."""
        chunks = []
        for start in range(0, len(device_rows), self._max_batch):
            rows = device_rows[start : start + self._max_batch]
            b = _bucket(len(rows), self._max_batch)
            pad_cls = np.zeros((b, self._max_len), dtype=np.int32)
            pad_len = np.zeros(b, dtype=np.int32)
            pad_cls[: len(rows)] = cls_ids[rows]
            pad_len[: len(rows)] = lens[rows]
            self.stats.note_xfer(h2d_bytes=pad_cls.nbytes + pad_len.nbytes)
            if self._pallas_prep is not None:
                packed = pallas_nfa.match_batch_pallas(
                    self._pallas_prep, pad_cls, pad_len,
                    interpret=self._pallas_interpret, packed=True,
                )
            else:
                packed = nfa_jax.match_batch_packed(
                    self._params, pad_cls, pad_len, self.compiled.n_rules
                )
            chunks.append((rows, packed))
        return chunks

    def _single_stage_collect(self, n: int, chunks: list) -> np.ndarray:
        bits = np.zeros((n, self.compiled.n_rules), dtype=np.uint8)
        for rows, packed in chunks:
            packed_np = np.asarray(packed)
            self.stats.note_xfer(d2h_bytes=packed_np.nbytes)
            out = np.unpackbits(
                packed_np, axis=1, count=self.compiled.n_rules
            )
            bits[rows] = out[: len(rows)]
        return bits

    def _single_stage_bits(
        self, n: int, cls_ids, lens, host_eval, device_rows
    ) -> np.ndarray:
        """Full-NFA match bitmap for the single-device path (also the
        prefilter's overflow fallback — it has no capacity to exceed)."""
        return self._single_stage_collect(
            n, self._single_stage_submit(cls_ids, lens, device_rows)
        )

    def _rule_pos(self, host: str) -> Dict[int, int]:
        """{rule id -> its position in the host's per-site-then-global
        order (regex_rate_limiter.go:175-211)} — O(matched-ids) per row.

        Hosts with no per-site rules share one global dict — the host
        field comes from attacker-controlled log lines, so caching per
        unknown host would be an unbounded-memory hole; the per-site cache
        is bounded by the config's site list."""
        if host not in self._per_site_idx:
            return self._global_pos
        d = self._rule_pos_cache.get(host)
        if d is None:
            d = {
                int(x): k
                for k, x in enumerate(self._per_site_idx[host] + self._global_idx)
            }
            self._rule_pos_cache[host] = d
        return d

    def _apply_matched_rule(self, rule: RegexWithRate, p: ParsedLine) -> RuleResult:
        """applyRegexToLog after a confirmed regex match
        (regex_rate_limiter.go:240-269) — identical to cpu_ref."""
        result = RuleResult(rule_name=rule.rule, regex_match=True)
        if rule.hosts_to_skip.get(p.host):
            result.skip_host = True
            return result
        result.skip_host = False
        seen_ip, rate_limit_result = self.rate_limit_states.apply(
            p.ip, rule, p.timestamp_ns
        )
        result.seen_ip = seen_ip
        result.rate_limit_result = rate_limit_result
        if rate_limit_result.exceeded:
            self.banner.ban_or_challenge_ip(self.config, p.ip, rule.decision, p.host)
            self.banner.log_regex_ban(
                self.config, p.timestamp_ns / 1e9, p.ip, rule.rule, p.rest, rule.decision
            )
            provenance.record(
                provenance.SOURCE_RATE_LIMIT, p.ip, rule.decision,
                rule=rule.rule, hits=rule.hits_per_interval + 1,
            )
        return result


def _bucket(n: int, cap: int) -> int:
    """Pad batch sizes to powers of two to bound jit recompiles."""
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return min(b, max(cap, _MIN_BUCKET))
