"""Device-side fixed-window rate-limit counters (SURVEY.md §7.1 hard part #3).

The reference applies its per-(IP, rule) fixed-window counters serially, one
matched line at a time, under a mutex (/root/reference/internal/
rate_limit.go:37-78). This module keeps the counters resident on the TPU as
flat [capacity * n_rules] arrays and folds a whole batch of match events into
them in one jitted step:

  match bitmap [B, R]  (straight from the NFA kernel, never pulled to host)
    → mask by per-host rule applicability / hosts_to_skip
    → compact to an event list (line, rule) via fixed-capacity nonzero
    → stable-sort by (slot, rule) key — row-major nonzero order IS the
      reference's processing order (per-site rule ids precede global ids,
      so (line, rule_id) ascending == the per-site-then-global loop of
      regex_rate_limiter.go:175-211)
    → one lax.scan over the sorted events: per segment, load the persistent
      (hits, start) state, replay the exact window transitions, flag
      exceeded events, write the segment's final state back
    → return the compact per-event (match_type, exceeded, seen_ip) plus the
      bit-packed match bitmap for host-side result reconstruction.

Exactness: the host oracle (decisions/rate_limit.py, itself a port of
rate_limit.go) compares int64 nanoseconds; TPUs have no native int64, so
timestamps ride as (seconds, nanoseconds) int32 pairs and every comparison
uses borrow arithmetic — bit-identical to the int64 path, including the
contract quirks: window restart strictly-greater-than interval, hits reset
to 0 (not 1) on exceed, FirstTime/OutsideInterval/InsideInterval match
types, and seen_ip = "the IP had any state before this event".

IP slots are assigned host-side (dict + LRU); evicting a slot queues a
device-side row clear that runs in the next maintenance step, so the device
never needs a host round-trip mid-batch. Eviction is LOSSLESS: a host-side
shadow (updated from each batch's event-final states, which the scan
computes anyway) holds every (ip, rule) counter, and a re-admitted IP's
rows are scattered back onto the device before its next events — beyond
`matcher_window_capacity` distinct IPs the matcher degrades to slower,
never to wrong (rate_limit.go:37-78 never forgets state, so neither do we).
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from banjax_tpu.config.schema import RegexWithRate
from banjax_tpu.decisions.rate_limit import (
    NumHitsAndIntervalStart,
    RateLimitMatchType,
)

_NS_PER_S = 1_000_000_000

_MIN_ROW_BUCKET = 64


def _bucket_rows(n: int) -> int:
    """Pad batch row counts to powers of two: _apply_step is jitted with the
    batch arrays' shapes as trace keys, so unbucketed sizes would compile a
    fresh segmented-scan program per distinct B (unbounded jit-cache growth
    in the hot path). Pad rows carry bits=0 and so produce no events."""
    b = _MIN_ROW_BUCKET
    while b < n:
        b <<= 1
    return b


def split_ns(ts_ns) -> Tuple[np.ndarray, np.ndarray]:
    """int64 ns → (seconds, subsecond ns) int32 pair; exact for epoch times."""
    ts_ns = np.asarray(ts_ns, dtype=np.int64)
    s, ns = np.divmod(ts_ns, _NS_PER_S)  # floored divmod: ns always in [0, 1e9)
    return s.astype(np.int32), ns.astype(np.int32)


def _pair_gt(a_s, a_ns, b_s, b_ns):
    """(a_s, a_ns) > (b_s, b_ns) lexicographically — int64 compare, split."""
    return (a_s > b_s) | ((a_s == b_s) & (a_ns > b_ns))


def _pair_sub(a_s, a_ns, b_s, b_ns):
    """(a - b) as a normalized (s, ns) pair with borrow; may be negative s."""
    ds = a_s - b_s
    dns = a_ns - b_ns
    borrow = dns < 0
    return ds - borrow.astype(ds.dtype), dns + borrow.astype(dns.dtype) * _NS_PER_S


@dataclasses.dataclass
class DeviceWindowState:
    """The donated device arrays (flat key = slot * n_rules + rule)."""

    hits: jnp.ndarray      # [cap * R] int32
    start_s: jnp.ndarray   # [cap * R] int32
    start_ns: jnp.ndarray  # [cap * R] int32
    valid: jnp.ndarray     # [cap * R] bool — state exists for this key
    ip_seen: jnp.ndarray   # [cap] bool — slot has any state (seen_ip flag)


@jax.jit
def _count_events(bits, active_table, host_idx):
    """Pre-pass: event count — the overflow check before any state mutation."""
    fire = (bits != 0) & active_table[host_idx]
    return fire.sum(dtype=jnp.int32)


def _window_step(carry, xs):
    """One event of the fixed-window recurrence (rate_limit.go:37-78 with
    the reset-to-0-on-exceed quirk), segment boundaries reloading the
    persistent state.  Module-level and pure on purpose: the XLA
    `lax.scan` below and the Pallas single-kernel scan
    (kernels/fused_match_window.py) both lower from THIS definition, so
    the two paths cannot drift semantically."""
    c_hits, c_ss, c_sns = carry
    (b, gh, gs, gn, gv, ets, etn, lim, ivs, ivn, is_pad) = xs
    h0 = jnp.where(b, gh, c_hits)
    s0 = jnp.where(b, gs, c_ss)
    n0 = jnp.where(b, gn, c_sns)
    have = jnp.where(b, gv, True)

    ds, dns = _pair_sub(ets, etn, s0, n0)
    outside = have & _pair_gt(ds, dns, ivs, ivn)
    restart = ~have | outside
    h1 = jnp.where(restart, jnp.int32(1), h0 + 1)
    s1 = jnp.where(restart, ets, s0)
    n1 = jnp.where(restart, etn, n0)
    exceeded = h1 > lim
    h2 = jnp.where(exceeded, jnp.int32(0), h1)
    mtype = jnp.where(
        ~have, jnp.int32(0), jnp.where(outside, jnp.int32(1), jnp.int32(2))
    )
    # padding events must not perturb the carry (they share key cap_r,
    # so they're their own segment — but keep them inert regardless)
    h2 = jnp.where(is_pad, c_hits, h2)
    s1 = jnp.where(is_pad, c_ss, s1)
    n1 = jnp.where(is_pad, c_sns, n1)
    return (h2, s1, n1), (h2, s1, n1, mtype, exceeded)


def _apply_core(
    state: DeviceWindowState,
    bits: jnp.ndarray,         # [B, R] uint8/bool match bitmap (device)
    active_table: jnp.ndarray,  # [H, R] bool — rule applicable & not hosts_to_skip
    host_idx: jnp.ndarray,     # [B] int32 row of active_table per line
    slot_ids: jnp.ndarray,     # [B] int32 (slot per line)
    ts_s: jnp.ndarray,         # [B] int32
    ts_ns: jnp.ndarray,        # [B] int32
    limits: jnp.ndarray,       # [R] int32 hits_per_interval
    iv_s: jnp.ndarray,         # [R] int32 interval seconds part
    iv_ns: jnp.ndarray,        # [R] int32 interval ns part
    *,
    n_rules: int,
    max_events: int,
    gate=None,                 # scalar bool: False drops EVERY state write
    scan_fn=None,              # None = lax.scan over _window_step
):
    """The traceable window-apply body — composable inside a larger jit
    (the fused matcher+windows pipeline) as well as the standalone
    _apply_step below. Caller guarantees evictions/restores already ran
    (_maintenance_step). `gate` supports overflow handling under buffer
    donation: when False, all scatters drop (indices pushed out of range)
    so the donated state passes through bit-identical and the caller can
    rerun the batch through the splitting path — no state copy needed.
    `scan_fn(init, xs) -> (f_hits, f_ss, f_sns, mtype, exceeded)` swaps
    the event recurrence for an alternative lowering of _window_step —
    the single-kernel path passes the Pallas scan from
    kernels/fused_match_window.py; None keeps the XLA lax.scan."""
    cap_r = state.hits.shape[0]
    valid = state.valid
    ip_seen = state.ip_seen

    fire = (bits != 0) & active_table[host_idx]

    # 1. fixed-capacity compaction in row-major (= reference processing) order
    lines, rules = jnp.nonzero(
        fire, size=max_events, fill_value=(jnp.int32(-1), jnp.int32(-1))
    )
    pad = lines < 0
    slot = jnp.where(pad, jnp.int32(0), slot_ids[lines])
    key = jnp.where(pad, jnp.int32(cap_r), slot * n_rules + rules)  # pad sorts last
    seq = jnp.arange(max_events, dtype=jnp.int32)

    # 2. stable sort by key (ties keep row-major order)
    order = jnp.lexsort((seq, key))
    key_s = key[order]
    lines_s = lines[order]
    rules_s = jnp.where(key_s >= cap_r, jnp.int32(0), rules[order])
    e_ts_s = ts_s[jnp.maximum(lines_s, 0)]
    e_ts_ns = ts_ns[jnp.maximum(lines_s, 0)]
    pad_s = key_s >= cap_r

    # seen_ip: slot already seen on device, or an earlier event in this batch
    # touched the slot (reference: the per-IP dict exists, rate_limit.go:72-79)
    first_seq = jnp.full((state.ip_seen.shape[0],), max_events, dtype=jnp.int32)
    first_seq = first_seq.at[slot].min(
        jnp.where(pad, max_events, seq), mode="drop"
    )
    seen_ip_ev = ip_seen[slot] | (seq > first_seq[slot])  # post-eviction flags
    seen_ip_s = seen_ip_ev[order]

    # 3. segment boundaries + persistent state gather per event
    prev_key = jnp.concatenate([jnp.full((1,), -1, dtype=key_s.dtype), key_s[:-1]])
    boundary = key_s != prev_key
    g_hits = state.hits[jnp.minimum(key_s, cap_r - 1)]
    g_ss = state.start_s[jnp.minimum(key_s, cap_r - 1)]
    g_sns = state.start_ns[jnp.minimum(key_s, cap_r - 1)]
    g_valid = valid[jnp.minimum(key_s, cap_r - 1)] & ~pad_s

    lim_e = limits[rules_s]
    ivs_e = iv_s[rules_s]
    ivns_e = iv_ns[rules_s]

    init = (jnp.int32(0), jnp.int32(0), jnp.int32(0))
    xs = (
        boundary, g_hits, g_ss, g_sns, g_valid,
        e_ts_s, e_ts_ns, lim_e, ivs_e, ivns_e, pad_s,
    )
    if scan_fn is None:
        _, (f_hits, f_ss, f_sns, mtype, exceeded) = jax.lax.scan(
            _window_step, init, xs
        )
    else:
        f_hits, f_ss, f_sns, mtype, exceeded = scan_fn(init, xs)

    # 4. write back each segment's final state (last event of each key)
    next_key = jnp.concatenate([key_s[1:], jnp.full((1,), -2, dtype=key_s.dtype)])
    is_last = (key_s != next_key) & ~pad_s
    wb_key = jnp.where(is_last, key_s, jnp.int32(cap_r))  # drop non-last
    seen_idx = jnp.where(pad, state.ip_seen.shape[0], slot)
    if gate is not None:
        wb_key = jnp.where(gate, wb_key, jnp.int32(cap_r))
        seen_idx = jnp.where(gate, seen_idx, state.ip_seen.shape[0])
    hits = state.hits.at[wb_key].set(f_hits, mode="drop")
    start_s = state.start_s.at[wb_key].set(f_ss, mode="drop")
    start_ns = state.start_ns.at[wb_key].set(f_sns, mode="drop")
    valid = valid.at[wb_key].set(True, mode="drop")
    ip_seen = ip_seen.at[seen_idx].set(True, mode="drop")

    new_state = DeviceWindowState(
        hits=hits, start_s=start_s, start_ns=start_ns, valid=valid, ip_seen=ip_seen
    )
    out = {
        "line": lines_s,
        "rule": jnp.where(pad_s, jnp.int32(-1), rules_s),
        "match_type": mtype,
        "exceeded": exceeded & ~pad_s,
        "seen_ip": seen_ip_s,
        # per-event FINAL counter state: feeds the host shadow that makes
        # eviction lossless (last event per key carries the written state)
        "hits": f_hits,
        "start_s": f_ss,
        "start_ns": f_sns,
    }
    return new_state, out


@functools.partial(
    jax.jit,
    static_argnames=("n_rules", "max_events"),
    donate_argnums=(0,),
)
def _apply_step(state, bits, active_table, host_idx, slot_ids, ts_s, ts_ns,
                limits, iv_s, iv_ns, *, n_rules, max_events):
    return _apply_core(
        state, bits, active_table, host_idx, slot_ids, ts_s, ts_ns,
        limits, iv_s, iv_ns, n_rules=n_rules, max_events=max_events,
    )


@functools.partial(jax.jit, donate_argnums=(0,))
def _maintenance_step(
    state: DeviceWindowState,
    ev_keys: jnp.ndarray,   # [Ke] int32 flat keys to invalidate (cap_r = none)
    ev_slots: jnp.ndarray,  # [K] int32 slots to clear seen flag (cap = none)
    r_keys: jnp.ndarray,    # [Kr] int32 flat keys to restore (cap_r = none)
    r_hits: jnp.ndarray,    # [Kr] int32
    r_ss: jnp.ndarray,      # [Kr] int32
    r_sns: jnp.ndarray,     # [Kr] int32
    r_slots: jnp.ndarray,   # [K2] int32 slots to mark seen (cap = none)
):
    """Evictions THEN restores, in one dispatch: a slot can be evicted and
    immediately reassigned+restored between two apply steps, so the order
    within this step is what keeps the restored state from being cleared."""
    valid = state.valid.at[ev_keys].set(False, mode="drop")
    ip_seen = state.ip_seen.at[ev_slots].set(False, mode="drop")
    hits = state.hits.at[r_keys].set(r_hits, mode="drop")
    start_s = state.start_s.at[r_keys].set(r_ss, mode="drop")
    start_ns = state.start_ns.at[r_keys].set(r_sns, mode="drop")
    valid = valid.at[r_keys].set(True, mode="drop")
    ip_seen = ip_seen.at[r_slots].set(True, mode="drop")
    return DeviceWindowState(
        hits=hits, start_s=start_s, start_ns=start_ns, valid=valid,
        ip_seen=ip_seen,
    )


jax.tree_util.register_dataclass(
    DeviceWindowState,
    data_fields=["hits", "start_s", "start_ns", "valid", "ip_seen"],
    meta_fields=[],
)


@dataclasses.dataclass
class WindowEvent:
    """One applied (line, rule) window transition, in reference order."""

    line: int
    rule_id: int
    match_type: RateLimitMatchType
    exceeded: bool
    seen_ip: bool


class DeviceWindows:
    """Device-resident RegexRateLimitStates with host slot management.

    Authoritative when `matcher_device_windows: true`; mirrors the host
    class's introspection surface (`get`, `format_states`, `__len__`) by
    pulling only the requested slots back from the device.
    """

    # auto-size memory budget: device state is 13 bytes per (slot, rule)
    # (3x int32 + valid bool) plus [capacity] ip_seen; cap the flat arrays
    # well under the v5e-1's 16 GB HBM so the matcher never squeezes the
    # kernels' working set
    AUTO_START_CAPACITY = 16384
    AUTO_MEM_BUDGET_BYTES = 2 << 30

    def __init__(
        self,
        rules: Sequence[RegexWithRate],
        capacity: int = 16384,  # matcher_window_capacity; 0 = auto-size
        max_events: int = 4096,
        native_slotmgr: bool = True,
        warm_tier=None,             # pre-built tier object (tests inject)
        warm_tier_enabled: bool = False,
        warm_tier_capacity: int = 1 << 20,
    ):
        self.n_rules = max(1, len(rules))
        # capacity 0 = auto: start small, double on occupancy pressure
        # (observed distinct-IP rate) up to the memory budget — an eviction
        # is forced only once the budget ceiling is reached
        self.auto_grow = capacity <= 0
        if self.auto_grow:
            # the budget is a CEILING: a huge ruleset shrinks both the
            # ceiling and the start size (the 256-slot floor just keeps the
            # table functional); the start never exceeds the budget
            self.max_capacity = max(
                256, int(self.AUTO_MEM_BUDGET_BYTES // (13 * self.n_rules))
            )
            capacity = min(self.AUTO_START_CAPACITY, self.max_capacity)
        else:
            self.max_capacity = capacity
        self.grow_count = 0
        self.capacity = capacity
        # a single line can fire every rule; max_events >= n_rules makes the
        # overflow split terminate at B=1
        self.max_events = max(max_events, self.n_rules)
        self._lock = threading.Lock()

        limits = np.zeros(self.n_rules, dtype=np.int32)
        iv_s = np.zeros(self.n_rules, dtype=np.int32)
        iv_ns = np.zeros(self.n_rules, dtype=np.int32)
        iv_total = np.zeros(self.n_rules, dtype=np.int64)
        self._rule_names: List[str] = []
        for i, r in enumerate(rules):
            limits[i] = r.hits_per_interval
            iv_s[i], iv_ns[i] = divmod(int(r.interval_ns), _NS_PER_S)
            iv_total[i] = int(r.interval_ns)
            self._rule_names.append(r.rule)
        self._limits = jnp.asarray(limits)
        self._iv_s = jnp.asarray(iv_s)
        self._iv_ns = jnp.asarray(iv_ns)
        # host copies for the refused-row window apply (apply_host_events
        # replicates _window_step in exact int64 arithmetic)
        self._limits_np = limits
        self._iv_total_np = iv_total

        # --- mega-state tiering (warm tier + cold-tier admission) ---
        # Warm tier: evicted hot-tier state spills HERE (shadow entry
        # moves into the bounded shm table) instead of accumulating in
        # the unbounded host shadow; a returning IP refills
        # byte-identically on slot claim.  None = warm tier off — the
        # pre-tiering behavior (shadow keeps everything) is unchanged.
        self._warm = warm_tier
        if self._warm is None and warm_tier_enabled:
            from banjax_tpu.native.shm import create_warm_tier

            # steal horizon: twice the widest rule window — an entry
            # whose every window could have expired is semantically a
            # restart-as-first-seen, so stealing it loses nothing
            expiry = max(60 * _NS_PER_S, 2 * int(iv_total.max() or 0))
            self._warm = create_warm_tier(
                capacity=warm_tier_capacity,
                max_rules=self.n_rules,
                expiry_ns=expiry,
            )
        self.warm_spills = 0
        self.warm_refills = 0
        # Cold-tier admission bookkeeping (admission_mask): refused rows
        # are counted, never dropped — the runner still matches and
        # host-applies them.  FP accounting: a slot claimed on a sketch
        # estimate is marked; if its tenure ends with the IP having
        # matched nothing, the admission was a sketch overcount.
        self.slot_refusals = 0
        self.sketch_admissions = 0
        self.sketch_fp_evaluated = 0
        self.sketch_fp_count = 0
        self._sketch_pending: set = set()
        self._sketch_slots: Dict[int, bool] = {}

        self._slots: Dict[str, int] = {}  # ip → slot
        # batch-granular recency per slot (see slots_for_unique_ips)
        self._last_used = np.zeros(capacity, dtype=np.int64)
        self._batch_seq = 0
        self._slot_ip: Dict[int, str] = {}
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        # native slot manager (native/slotmgr.c): the whole per-distinct-
        # IP assignment loop — hash lookup, free-stack pop, LRU eviction —
        # runs as one C call per batch over the unique-IP array, with
        # exact Python-path parity (tests/unit/test_slotmgr.py).  The
        # dict loop below stays as the fallback (no C compiler) and the
        # differential oracle.  _slot_ip mirrors slot→ip in BOTH modes
        # (shadow updates and restores need the strings); _slots/_free
        # are dict-path-only.
        self._sm = None
        self.slotmgr_native = False
        if native_slotmgr:
            from banjax_tpu.native import slotmgr as _slotmgr

            self._sm = _slotmgr.create(capacity)
            self.slotmgr_native = self._sm is not None
        self._pending_evict: List[int] = []
        self._pending_restore: List[Tuple[int, str]] = []
        # slots handed out by slots_for_ips stay pinned until the matching
        # apply_bitmap consumes them, so a second caller's allocation can
        # never evict a slot whose events are still in flight
        self._pin_counts = np.zeros(capacity, dtype=np.int32)
        # spill-on-evict: the host shadow below keeps every counter, so
        # eviction only costs performance (a restore on re-admission), never
        # correctness; this counter surfaces the capacity pressure
        self.eviction_count = 0
        # Host shadow of the device counters: ip → (rule_id → (hits, s, ns)),
        # both dicts in first-event insertion order — exactly the reference
        # host dict's shape (rate_limit.go:37-78, which never forgets).
        # Updated from every batch's event-final states (the scan computes
        # them anyway for the device write-back), so it costs O(events) host
        # work per batch, not a device pull. It is the authoritative source
        # for introspection (get/format_states/__len__) and the restore
        # source when an evicted IP is re-admitted. Memory is O(distinct
        # (ip, rule) pairs with events) — the reference's own asymptotic.
        self._shadow: "Dict[str, OrderedDict]" = {}
        self._state = self._fresh_state()

    def _fresh_state(self) -> DeviceWindowState:
        cap_r = self.capacity * self.n_rules
        return DeviceWindowState(
            hits=jnp.zeros((cap_r,), dtype=jnp.int32),
            start_s=jnp.zeros((cap_r,), dtype=jnp.int32),
            start_ns=jnp.zeros((cap_r,), dtype=jnp.int32),
            valid=jnp.zeros((cap_r,), dtype=jnp.bool_),
            ip_seen=jnp.zeros((self.capacity,), dtype=jnp.bool_),
        )

    # ---- slot management (host) ----

    def slot_for_ip(self, ip: str) -> Optional[int]:
        """Slot for one IP, or None if every slot is pinned by in-flight
        batches (transient: retry after those batches' apply_bitmap runs)."""
        slots = self.slots_for_ips([ip])
        if slots is None:
            return None
        self._release_pins(slots)  # lookup only — no apply_bitmap will follow
        return int(slots[0])

    def release_pins(self, slot_ids) -> None:
        """Release a batch's pins when apply_bitmap will NOT be called
        (apply_bitmap releases its own batch's pins on every path — call
        exactly one of the two, never both)."""
        self._release_pins(slot_ids)

    def slots_for_ips(self, ips: Sequence[str]) -> Optional[np.ndarray]:
        """Assign a slot per IP for one batch, atomically.

        Slots touched by THIS batch are pinned: evicting and reusing a slot
        mid-batch would fold two different IPs' counters into the same
        (slot, rule) keys in one scan. If an allocation would have to evict
        a pinned slot, returns None — the caller must split the batch.
        """
        # dedup first: batches repeat IPs heavily, and every per-line dict
        # touch (get + move_to_end + pin bookkeeping) at 65k lines costs
        # more than the device apply itself. One slot decision per DISTINCT
        # ip, then a vectorized gather back to line order. LRU semantics
        # are unchanged: each distinct ip is marked used once per batch
        # (intra-batch recency order among members is not observable).
        uniq: "OrderedDict[str, int]" = OrderedDict()
        inv = np.empty(len(ips), dtype=np.int32)
        for i, ip in enumerate(ips):
            k = uniq.get(ip)
            if k is None:
                k = len(uniq)
                uniq[ip] = k
            inv[i] = k
        uslots = self.slots_for_unique_ips(list(uniq))
        if uslots is None:
            return None
        return uslots[inv] if len(ips) else np.empty(0, dtype=np.int32)

    def slots_for_unique_ips(
        self, ips: Sequence[str]
    ) -> Optional[np.ndarray]:
        """slots_for_ips for a DISTINCT ip list (one slot decision + one
        pin per entry). Callers that already hold a unique table + inverse
        (the runner's vectorized gate) use this directly and gather.

        Recency is batch-granular: hits record this batch's sequence
        number in a vectorized `last_used` array (no per-hit order-list
        churn); eviction scans argmin(last_used) over evictable slots —
        O(capacity) but evictions are rare by design (auto-grow absorbs
        distinct-IP pressure first), and which victim is chosen is not a
        parity surface (spill is lossless either way — though the native
        manager reproduces the argmin victim exactly, so the parity fuzz
        can compare slot ids verbatim)."""
        with self._lock:
            self._batch_seq += 1
            if self._sm is not None:
                return self._slots_unique_native_locked(ips)
            out = np.empty(len(ips), dtype=np.int32)
            misses: List[int] = []
            get = self._slots.get
            for i, ip in enumerate(ips):
                slot = get(ip)
                if slot is None:
                    misses.append(i)
                    out[i] = -1
                else:
                    out[i] = slot
            if len(misses) < len(ips):
                hits = out[out >= 0]
                self._last_used[hits] = self._batch_seq
            for i in misses:
                ip = ips[i]
                if (
                    not self._free
                    and self.auto_grow
                    and self.capacity < self.max_capacity
                ):
                    self._grow_locked(
                        min(self.capacity * 2, self.max_capacity)
                    )
                if not self._free:
                    slot = self._evict_one_locked(out)
                    if slot is None:
                        return None  # every slot pinned
                else:
                    slot = self._free.pop()
                self._slots[ip] = slot
                self._slot_ip[slot] = ip
                self._last_used[slot] = self._batch_seq
                if self._sketch_pending and ip in self._sketch_pending:
                    self._sketch_pending.discard(ip)
                    self._sketch_slots[slot] = True
                if ip in self._shadow:
                    # previously-evicted IP returns: its counters re-enter
                    # the device in the next maintenance step, BEFORE any
                    # of this batch's events for it are applied
                    self._pending_restore.append((slot, ip))
                elif self._warm is not None and len(self._warm):
                    self._refill_from_warm_locked(slot, ip)
                out[i] = slot
            # out holds DISTINCT slots (distinct ips map to distinct
            # slots), so a vectorized increment pins each exactly once
            self._pin_counts[out] += 1
            return out

    def admission_mask(
        self,
        ips: Sequence[str],
        estimates: Optional[np.ndarray] = None,
        min_estimate: int = 1,
        counts: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """Cold-tier slot admission over a DISTINCT ip list: bool [n],
        True = the IP may claim a hot-tier slot this batch.

        Admission order (first hit wins):
          1. already hot (slot assigned) — membership probe only, NO
             recency stamp, so a refused batch cannot refresh its probe
             victims' LRU position;
          2. known state elsewhere (host shadow or warm tier) — a
             returning IP always re-enters (the refill path needs the
             slot);
          3. unseen: admitted iff the traffic sketch plausibly puts it
             over the cheapest rule threshold (estimates[i] >=
             min_estimate).  The count-min estimate never undercounts,
             so a real offender is delayed at most min_estimate lines —
             never missed.

        `estimates=None` admits every unseen IP (admission off).
        `counts` (per-ip row counts) weights the refusal counter by
        rows, not distinct IPs.  Refused rows are NOT dropped — the
        runner matches them device-statelessly and applies their window
        transitions host-side (apply_host_events)."""
        n = len(ips)
        with self._lock:
            if n == 0:
                return np.zeros(0, dtype=bool)
            if self._sm is not None:
                admit = self._sm.contains_batch(ips)
            else:
                slots = self._slots
                admit = np.fromiter(
                    (ip in slots for ip in ips), dtype=bool, count=n
                )
            unknown = np.flatnonzero(~admit)
            if len(unknown):
                shadow = self._shadow
                if shadow:
                    sh = np.fromiter(
                        (ips[int(i)] in shadow for i in unknown),
                        dtype=bool, count=len(unknown),
                    )
                    admit[unknown[sh]] = True
                    unknown = unknown[~sh]
            if (
                len(unknown)
                and self._warm is not None
                and len(self._warm)
            ):
                wm = self._warm.contains_batch(
                    [ips[int(i)] for i in unknown]
                )
                admit[unknown[wm]] = True
                unknown = unknown[~wm]
            if len(unknown):
                if estimates is None:
                    admit[unknown] = True
                else:
                    est_ok = (
                        np.asarray(estimates)[unknown]
                        >= min_estimate
                    )
                    admitted = unknown[est_ok]
                    admit[admitted] = True
                    for i in admitted:
                        self._sketch_pending.add(ips[int(i)])
                    self.sketch_admissions += int(est_ok.sum())
                    refused = unknown[~est_ok]
                    if counts is not None:
                        self.slot_refusals += int(
                            np.asarray(counts)[refused].sum()
                        )
                    else:
                        self.slot_refusals += len(refused)
            return admit

    def _slots_unique_native_locked(self, ips: Sequence[str]) -> Optional[np.ndarray]:
        """slots_for_unique_ips via the native manager: one C lookup pass
        (hits touched), the Python growth chain between passes, one C
        placement pass (free stack, then exact-argmin eviction).  Python
        work is O(misses + evictions) dict bookkeeping only."""
        sm = self._sm
        slots, miss_idx, ctx = sm.lookup_batch(
            ips, self._batch_seq, self._last_used
        )
        n_miss = len(miss_idx)
        if n_miss:
            # replicate the dict path's per-miss doubling chain: grow
            # while the free pool cannot absorb the remaining misses and
            # the ceiling allows — the same final capacity the
            # grow-on-empty loop reaches
            new_cap = self.capacity
            free_cnt = new_cap - len(self._slot_ip)
            steps = 0
            while (
                free_cnt < n_miss
                and self.auto_grow
                and new_cap < self.max_capacity
            ):
                step = min(new_cap * 2, self.max_capacity)
                free_cnt += step - new_cap
                new_cap = step
                steps += 1
            if new_cap != self.capacity:
                self._grow_locked(new_cap)
                # one coalesced realloc, but DeviceWindowsGrows counts
                # logical doublings — keep the metric comparable with the
                # dict path's grow-per-miss loop
                self.grow_count += steps - 1
        placed_idx, evicted, ok = sm.place_misses(
            ctx, slots, miss_idx, self._batch_seq, self._pin_counts,
            self._last_used,
        )
        if len(evicted):
            ev = [int(s) for s in evicted]
            for s in ev:
                self._note_eviction_locked(s, self._slot_ip.pop(s, None))
            self._pending_evict.extend(ev)
            if self.eviction_count == 0:
                self._warn_first_eviction()
            self.eviction_count += len(ev)
        if len(placed_idx):
            shadow = self._shadow
            pend_restore = self._pending_restore
            slot_ip = self._slot_ip
            idx_l = placed_idx.tolist()
            slot_l = slots[placed_idx].tolist()
            ip_l = list(map(ips.__getitem__, idx_l))
            # C-speed mirror update: at the all-distinct-IP shape this
            # loop IS the residual host cost, so no per-entry Python
            slot_ip.update(zip(slot_l, ip_l))
            pend_sketch = self._sketch_pending
            if pend_sketch:
                for slot, ip in zip(slot_l, ip_l):
                    if ip in pend_sketch:
                        pend_sketch.discard(ip)
                        self._sketch_slots[slot] = True
            # warm membership in ONE C probe over the placed ips; takes
            # only on hits — the all-distinct shape (misses everywhere)
            # pays one batch call, not a per-ip round-trip
            warm = self._warm
            in_warm = (
                warm.contains_batch(ip_l)
                if warm is not None and len(warm) else None
            )
            if shadow or in_warm is not None:
                for k, (slot, ip) in enumerate(zip(slot_l, ip_l)):
                    if ip in shadow:
                        # previously-evicted IP returns: counters re-enter
                        # the device in the next maintenance step, BEFORE
                        # any of this batch's events for it are applied
                        pend_restore.append((slot, ip))
                    elif in_warm is not None and in_warm[k]:
                        self._refill_from_warm_locked(slot, ip)
        if not ok:
            return None  # every eviction candidate pinned — split
        self._pin_counts[slots] += 1
        return slots

    def _warn_first_eviction(self) -> None:
        import logging

        hint = (
            "auto-size hit its memory-budget ceiling — "
            "more HBM or fewer rules would raise it"
            if self.auto_grow else
            "raise matcher_window_capacity (or set 0 = "
            "auto-size) to avoid the churn"
        )
        logging.getLogger(__name__).warning(
            "device-windows capacity (%d slots) exceeded; "
            "evicting LRU IP state to the host shadow "
            "(restored on re-admission — %s)",
            self.capacity, hint,
        )

    def _evict_one_locked(self, batch_slots: np.ndarray) -> Optional[int]:
        """Pick and evict the oldest evictable slot: assigned, not pinned
        by an in-flight batch, and not already handed to THIS batch
        (reusing one mid-batch would fold two IPs' counters together)."""
        used = np.full(self.capacity, np.iinfo(np.int64).max, dtype=np.int64)
        assigned = list(self._slot_ip)
        used[assigned] = self._last_used[assigned]
        used[self._pin_counts > 0] = np.iinfo(np.int64).max
        mine = batch_slots[batch_slots >= 0]
        if mine.size:
            used[mine] = np.iinfo(np.int64).max
        victim = int(np.argmin(used))
        if used[victim] == np.iinfo(np.int64).max:
            return None
        victim_ip = self._slot_ip.pop(victim)
        self._slots.pop(victim_ip)
        self._note_eviction_locked(victim, victim_ip)
        self._pending_evict.append(victim)
        if self.eviction_count == 0:
            self._warn_first_eviction()
        self.eviction_count += 1
        return victim

    def _note_eviction_locked(self, slot: int, ip: Optional[str]) -> None:
        """Tiering bookkeeping at hot-tier eviction: FP-evaluate a
        sketch-admitted tenure (no state at eviction = the sketch
        overcounted) and spill the victim's shadow entry into the warm
        tier.  On a warm-tier drop (probe window full of live records)
        the shadow KEEPS the entry — pre-tiering lossless behavior; the
        tier's `dropped` counter surfaces the sizing pressure."""
        if self._sketch_slots.pop(slot, False):
            self.sketch_fp_evaluated += 1
            if ip is None or ip not in self._shadow:
                self.sketch_fp_count += 1
        if self._warm is None or ip is None:
            return
        od = self._shadow.get(ip)
        if not od:
            return
        entries = [(rid, h, s, ns) for rid, (h, s, ns) in od.items()]
        if self._warm.put(ip, entries, time.time_ns()):
            del self._shadow[ip]
            self.warm_spills += 1

    def _refill_from_warm_locked(self, slot: int, ip: str) -> bool:
        """Move one IP's window vector warm → shadow and queue the device
        restore (the same next-maintenance path a shadow hit takes, so
        the counters re-enter the device BEFORE any of this batch's
        events for the IP)."""
        ent = self._warm.take(ip)
        if ent is None:
            return False
        self._shadow[ip] = OrderedDict(
            (rid, (h, s, ns)) for rid, h, s, ns in ent
        )
        self._pending_restore.append((slot, ip))
        self.warm_refills += 1
        return True

    def _grow_locked(self, new_capacity: int) -> None:
        """Double the slot table in place (auto-size): pad the flat device
        arrays with zeros and free-list the new high slots. Existing slot
        ids, pending evictions/restores, and the shadow are untouched; the
        only cost is one recompile of the apply programs at the new state
        shape (geometric growth bounds that to ~log2(max/start) compiles
        over the process lifetime)."""
        old_cap = self.capacity
        add = new_capacity - old_cap
        if add <= 0:
            return
        s = self._state
        pad_r = add * self.n_rules
        self._state = DeviceWindowState(
            hits=jnp.concatenate([s.hits, jnp.zeros(pad_r, jnp.int32)]),
            start_s=jnp.concatenate([s.start_s, jnp.zeros(pad_r, jnp.int32)]),
            start_ns=jnp.concatenate(
                [s.start_ns, jnp.zeros(pad_r, jnp.int32)]
            ),
            valid=jnp.concatenate([s.valid, jnp.zeros(pad_r, jnp.bool_)]),
            ip_seen=jnp.concatenate(
                [s.ip_seen, jnp.zeros(add, jnp.bool_)]
            ),
        )
        # pop() takes from the end: keep existing (lower) slots there so
        # allocation order is unchanged; new high slots drain last (the
        # native manager's free stack replicates the same order)
        if self._sm is not None:
            self._sm.grow(new_capacity)
        else:
            self._free = (
                list(range(new_capacity - 1, old_cap - 1, -1)) + self._free
            )
        self._last_used = np.concatenate(
            [self._last_used, np.zeros(add, dtype=np.int64)]
        )
        self._pin_counts = np.concatenate(
            [self._pin_counts, np.zeros(add, dtype=np.int32)]
        )
        self.capacity = new_capacity
        self.grow_count += 1
        import logging

        logging.getLogger(__name__).info(
            "device-windows auto-grow: %d -> %d slots (distinct-IP "
            "pressure; ceiling %d)",
            old_cap, new_capacity, self.max_capacity,
        )

    def _release_pins(self, slot_ids) -> None:
        with self._lock:
            # np.unique, not set(tolist()): per-line slot arrays repeat
            # heavily; one vectorized decrement per distinct slot
            uniq = np.unique(np.asarray(slot_ids, dtype=np.int64))
            self._pin_counts[uniq] -= 1
            np.maximum(self._pin_counts, 0, out=self._pin_counts)

    @property
    def occupancy(self) -> int:
        """IP slots currently assigned (capacity-pressure gauge)."""
        with self._lock:
            # _slot_ip mirrors assignments in both the native and dict
            # modes; _slots is dict-mode-only
            return len(self._slot_ip)

    def clear(self) -> None:
        """Hot-reload semantics: drop all counters (decision.go Clear analog)."""
        with self._lock:
            self._slots.clear()
            self._slot_ip.clear()
            self._shadow.clear()
            if self._sm is not None:
                self._sm.clear()
            else:
                self._free = list(range(self.capacity - 1, -1, -1))
            self._pending_evict = []
            self._pending_restore = []
            self._pin_counts = np.zeros(self.capacity, dtype=np.int32)
            self._last_used = np.zeros(self.capacity, dtype=np.int64)
            if self._warm is not None:
                self._warm.clear()
            self._sketch_pending.clear()
            self._sketch_slots.clear()
            self._state = self._fresh_state()

    def __len__(self) -> int:
        # parity with RegexRateLimitStates.__len__: IPs with any state —
        # including evicted ones (the reference never forgets; warm and
        # shadow populations are disjoint by construction)
        with self._lock:
            warm = len(self._warm) if self._warm is not None else 0
            return len(self._shadow) + warm

    # ---- tier gauges (obs/stats.py snapshot surface) ----

    @property
    def warm_occupancy(self) -> int:
        return len(self._warm) if self._warm is not None else 0

    @property
    def warm_capacity(self) -> int:
        return int(self._warm.capacity) if self._warm is not None else 0

    @property
    def warm_dropped(self) -> int:
        return int(self._warm.dropped) if self._warm is not None else 0

    @property
    def sketch_admission_fp_rate(self) -> float:
        """Of sketch-admitted slots whose tenure ENDED (evicted), the
        fraction that never matched any rule — the realized cost of
        count-min overcounting, measurable without ground truth."""
        if not self.sketch_fp_evaluated:
            return 0.0
        return self.sketch_fp_count / self.sketch_fp_evaluated

    # ---- the batch step ----

    def apply_bitmap(
        self,
        bits,                      # [B, R] device or host array
        slot_ids: np.ndarray,      # [B] int32
        ts_s: np.ndarray,
        ts_ns: np.ndarray,
        active_table,              # [H, R] bool (device-resident, cached by caller)
        host_idx: np.ndarray,      # [B] int32 — row of active_table per line
    ) -> List[WindowEvent]:
        """Apply one batch; returns the events in reference order.

        The event count is checked BEFORE any state mutation; a batch with
        more matched events than max_events is split in half and each half
        applied in order (a single line can produce at most n_rules events,
        so max_events >= n_rules guarantees termination). On return (even on
        error) the batch's slot pins from slots_for_ips are released."""
        try:
            return self._apply_bitmap_inner(
                bits, slot_ids, ts_s, ts_ns, active_table, host_idx
            )
        finally:
            self._release_pins(slot_ids)

    def _apply_bitmap_inner(
        self, bits, slot_ids, ts_s, ts_ns, active_table, host_idx
    ) -> List[WindowEvent]:
        bits = jnp.asarray(bits)
        active_table = jnp.asarray(active_table)
        host_idx = np.asarray(host_idx, dtype=np.int32)

        # bucket B up to a power of two so _count_events/_apply_step compile
        # once per bucket, not once per batch size (pad rows fire no events)
        B = bits.shape[0]
        Bp = _bucket_rows(B)
        if Bp != B:
            bits = jnp.pad(bits, ((0, Bp - B), (0, 0)))
            slot_ids = np.pad(np.asarray(slot_ids, dtype=np.int32), (0, Bp - B))
            ts_s = np.pad(np.asarray(ts_s, dtype=np.int32), (0, Bp - B))
            ts_ns = np.pad(np.asarray(ts_ns, dtype=np.int32), (0, Bp - B))
            host_idx = np.pad(host_idx, (0, Bp - B))

        n = _count_events(bits, active_table, jnp.asarray(host_idx))
        if int(n) > self.max_events:
            mid = B // 2
            ev1 = self._apply_bitmap_inner(
                bits[:mid], slot_ids[:mid], ts_s[:mid], ts_ns[:mid],
                active_table, host_idx[:mid],
            )
            ev2 = self._apply_bitmap_inner(
                bits[mid:B], slot_ids[mid:B], ts_s[mid:B], ts_ns[mid:B],
                active_table, host_idx[mid:B],
            )
            for e in ev2:
                e.line += mid
            return ev1 + ev2

        with self._lock:
            self._run_maintenance_locked()
            new_state, out = _apply_step(
                self._state,
                bits,
                active_table,
                jnp.asarray(host_idx),
                jnp.asarray(slot_ids, dtype=jnp.int32),
                jnp.asarray(ts_s, dtype=jnp.int32),
                jnp.asarray(ts_ns, dtype=jnp.int32),
                self._limits,
                self._iv_s,
                self._iv_ns,
                n_rules=self.n_rules,
                max_events=self.max_events,
            )
            self._state = new_state

            # The event pull AND the shadow update stay inside THIS lock
            # window: with two concurrent batches, writing the shadow in a
            # later acquisition could land the batches' final states in the
            # opposite order of their device application, and an eviction
            # would then restore the stale value as authoritative.
            line = np.asarray(out["line"])
            rule = np.asarray(out["rule"])
            mtype = np.asarray(out["match_type"])
            exceeded = np.asarray(out["exceeded"])
            seen = np.asarray(out["seen_ip"])
            f_hits = np.asarray(out["hits"])
            f_ss = np.asarray(out["start_s"])
            f_sns = np.asarray(out["start_ns"])
            live = np.flatnonzero(rule >= 0)
            # shadow update in (line, rule) order — the reference's
            # processing order — so dict INSERTION order matches the host
            # path's first-matched-event order (format_states parity; slot
            # numbering follows batch appearance, which can differ). Each
            # (ip, rule)'s last write is still its chronologically-last
            # event, i.e. the segment-final state written on device.
            order = np.lexsort((rule[live], line[live]))
            for k in live[order]:
                ip = self._slot_ip.get(int(slot_ids[int(line[k])]))
                if ip is None:  # unreachable while the batch is pinned
                    continue
                od = self._shadow.setdefault(ip, OrderedDict())
                od[int(rule[k])] = (int(f_hits[k]), int(f_ss[k]), int(f_sns[k]))

        events = [
            WindowEvent(
                line=int(line[k]),
                rule_id=int(rule[k]),
                match_type=RateLimitMatchType(int(mtype[k])),
                exceeded=bool(exceeded[k]),
                seen_ip=bool(seen[k]),
            )
            for k in live
        ]
        # reference order: by (line, rule_id) — per-site ids precede global
        events.sort(key=lambda e: (e.line, e.rule_id))
        return events

    def _run_maintenance_locked(self) -> None:
        """Drain queued evictions + restores into the device state (caller
        holds the lock). Sizes bucket to powers of two so the jit cache
        stays bounded; padded entries scatter out of range and drop."""
        if not self._pending_evict and not self._pending_restore:
            return
        cap_r = self.capacity * self.n_rules
        pend_ev = self._pending_evict
        pend_rs = self._pending_restore
        self._pending_evict = []
        self._pending_restore = []

        ev_keys_np = (
            (np.asarray(pend_ev, dtype=np.int64)[:, None] * self.n_rules
             + np.arange(self.n_rules, dtype=np.int64)[None, :]).ravel()
            .astype(np.int32)
            if pend_ev else np.empty(0, dtype=np.int32)
        )
        ev_slots_np = np.asarray(pend_ev, dtype=np.int32)
        r_keys: List[int] = []
        r_hits: List[int] = []
        r_ss: List[int] = []
        r_sns: List[int] = []
        r_slots: List[int] = []
        for slot, ip in pend_rs:
            if self._slot_ip.get(slot) != ip:
                # stale restore: the slot was re-evicted (and possibly
                # reassigned to a DIFFERENT ip) after this restore was
                # queued — scattering the old ip's counters now would
                # resurrect them into the new owner's rows
                continue
            od = self._shadow.get(ip)
            if not od:
                continue
            r_slots.append(slot)
            base = slot * self.n_rules
            for rid, (h, s, ns) in od.items():
                r_keys.append(base + rid)
                r_hits.append(h)
                r_ss.append(s)
                r_sns.append(ns)

        def _pad(vals, fill, k):
            arr = np.full((k,), fill, dtype=np.int32)
            arr[: len(vals)] = vals
            return jnp.asarray(arr)

        kk = 256  # pow2 bucket: bounded jit-cache, padded entries drop
        while kk < max(len(ev_keys_np), len(r_keys)):
            kk <<= 1
        ks = 256
        while ks < max(len(ev_slots_np), len(r_slots)):
            ks <<= 1
        self._state = _maintenance_step(
            self._state,
            _pad(ev_keys_np, cap_r, kk),
            _pad(ev_slots_np, self.capacity, ks),
            _pad(r_keys, cap_r, kk),
            _pad(r_hits, 0, kk),
            _pad(r_ss, 0, kk),
            _pad(r_sns, 0, kk),
            _pad(r_slots, self.capacity, ks),
        )

    # ---- refused-row host apply (cold-tier path) ----

    def apply_host_events(
        self, events: Sequence[Tuple[int, int, str, int]]
    ) -> List[WindowEvent]:
        """Window transitions for REFUSED rows — the slot-admission
        gate's classic per-line path.  `events` is a list of
        (row, rule_id, ip, ts_ns), pre-sorted by (row, rule_id)
        ascending — the reference processing order (per-site rule ids
        precede global ids, so this IS the per-site-then-global loop).

        Replicates _window_step exactly, in int64 nanoseconds (the host
        oracle's own arithmetic — the (s, ns) split on device is
        bit-identical to this by construction): restart strictly-
        greater-than interval, hits reset to 0 (not 1) on exceed,
        FirstTime/OutsideInterval/InsideInterval, seen_ip = "the IP had
        any state before this event".

        State home: the touched vectors are written back to the warm
        tier (shadow when the warm tier is off or the put drops), so a
        refused IP that matched anything is warm-resident — and
        therefore ADMITTED next batch (admission rule 2), which bounds
        the ban delay to the single batch in which the sketch estimate
        first crossed the threshold."""
        out: List[WindowEvent] = []
        if not events:
            return out
        with self._lock:
            touched: "Dict[str, OrderedDict]" = {}
            warm = self._warm
            warm_live = warm is not None and len(warm) > 0
            for row, rid, ip, ts_ns in events:
                od = touched.get(ip)
                if od is None:
                    od = self._shadow.get(ip)
                    if od is None and warm_live:
                        ent = warm.take(ip)
                        if ent is not None:
                            od = OrderedDict(
                                (r, (h, s, ns)) for r, h, s, ns in ent
                            )
                    if od is None:
                        od = OrderedDict()
                    touched[ip] = od
                seen = bool(od)
                st = od.get(rid)
                have = st is not None
                outside = False
                if have:
                    h0, s0, n0 = st
                    outside = (
                        int(ts_ns) - (s0 * _NS_PER_S + n0)
                        > int(self._iv_total_np[rid])
                    )
                if not have or outside:
                    h1 = 1
                    s1, n1 = divmod(int(ts_ns), _NS_PER_S)
                else:
                    h1 = h0 + 1
                    s1, n1 = s0, n0
                exceeded = h1 > int(self._limits_np[rid])
                od[rid] = (0 if exceeded else h1, s1, n1)
                mtype = 0 if not have else (1 if outside else 2)
                out.append(WindowEvent(
                    line=int(row), rule_id=int(rid),
                    match_type=RateLimitMatchType(mtype),
                    exceeded=bool(exceeded), seen_ip=seen,
                ))
            now_ns = time.time_ns()
            for ip, od in touched.items():
                if warm is not None:
                    entries = [
                        (rid, h, s, ns) for rid, (h, s, ns) in od.items()
                    ]
                    if warm.put(ip, entries, now_ns):
                        self._shadow.pop(ip, None)
                        self.warm_spills += 1
                        continue
                # warm off (or the put dropped): the shadow is the home
                self._shadow[ip] = od
        return out

    # ---- introspection parity with RegexRateLimitStates ----
    # The host shadow (updated from every batch's event-final states) is the
    # authoritative introspection source: no device pull, and it includes
    # evicted IPs — the reference host dict never forgets, so neither do we.

    def get(self, ip: str) -> Tuple[Dict[str, NumHitsAndIntervalStart], bool]:
        with self._lock:
            od = self._shadow.get(ip)
            if not od and self._warm is not None:
                ent = self._warm.peek(ip)
                if ent:
                    od = OrderedDict(
                        (r, (h, s, ns)) for r, h, s, ns in ent
                    )
            if not od:
                return {}, False  # seen at parse time but no event yet
            return {
                self._rule_names[rid]: NumHitsAndIntervalStart(
                    h, s * _NS_PER_S + ns
                )
                for rid, (h, s, ns) in od.items()
            }, True

    def format_states(self) -> str:
        with self._lock:
            rows = [(ip, list(od.items())) for ip, od in self._shadow.items()]
            if self._warm is not None and len(self._warm):
                # warm-resident IPs are disjoint from the shadow (spill
                # deletes the shadow entry), so this is a plain append
                for ip in self._warm.keys():
                    ent = self._warm.peek(ip)
                    if ent:
                        rows.append(
                            (ip, [(r, (h, s, ns)) for r, h, s, ns in ent])
                        )
        if not rows:
            return ""
        lines: List[str] = []
        for ip, states in rows:
            lines.append(f"{ip}:")
            for rid, (h, s, ns) in states:
                lines.append(f"\t{self._rule_names[rid]}:")
                lines.append(
                    f"\t\tNumHitsAndIntervalStart({h}, {s * _NS_PER_S + ns})"
                )
            lines.append("")
        return "\n".join(lines) + ("\n" if lines else "")
