"""Single-kernel fused match + window commit (ROADMAP item #1: kill the
~65 ms resolve pull).

The two-program fused path (matcher/fused_windows.py) splits every chunk
into program A (stateless match + overflow flags) and program B (window
commit) with a HOST decision between them: the drain thread pulls A's
flags (~65 ms fixed tunnel latency), checks overflow, and only then
dispatches B.  PRs 3-4 overlap that pull (resolve-ahead depth 2); this
module removes it.  One device program per chunk does

    match (the two-stage Pallas NFA scan, prefilter._match_core)
      → dense caller-order bitmap + sparse (row, rule) pairs
      → per-row live mask (staleness/abandon composed as an input)
      → window-hit accumulation + threshold-fire against the HBM-resident
        per-slot window state (windows._apply_core, state donated —
        tiles of it stage through VMEM inside the scan kernel below)
      → IN-KERNEL overflow gate: candidate / pair / event overflow (or a
        gated predecessor, see the chain scalar) drops every state write,
        so the donated state passes through bit-identical and the host
        replays the chunk through the existing classic fallback

and returns only a compact buffer — the [4] flags word ‖ sparse match
pairs ‖ always-rule bits ‖ the fired-event records — plus the
device-resident dense bitmap for the fallback.  The dense intermediate
never crosses the host boundary, there is no inter-program host turn,
and the drain's program-B dispatch disappears entirely: resolve becomes
a pure d2h pull of a buffer whose async copy started at submit.

Ordering without the resolve turn: program A was stateless, so the
two-program path could submit ahead and needed the resolve-turn
machinery to serialize B dispatches.  Here the state commit happens at
submit, and submits are already serialized (one device thread, chunks in
admission order), so device apply order == log order by construction.
The overflow hazard that forced the two-program split — chunk N
overflows, its classic re-apply would land AFTER an already-dispatched
chunk N+1 — is closed DEVICE-SIDE by the chain scalar: every kernel
takes its predecessor's ok flag and gates its own commit on it, so an
overflow poisons every already-dispatched successor in-device (they
pass state through untouched and replay classically, in order, on the
host).  The chain reseeds once no poisoned chunk is outstanding.

The window-transition recurrence runs as a Pallas kernel (`_scan_kernel`
— the "native tier" obligation of PAPER.md §0): event records staged
through VMEM, a fori_loop carry over the key-sorted events calling the
SAME `windows._window_step` the XLA lax.scan lowers, so the two paths
cannot drift.  `interpret=True` runs it as plain JAX — the CI path; the
compiled lowering is validated by the chip-attached round
(scripts/hw_session.sh step 4d).  `scan_selftest` proves the active
lowering bit-identical to lax.scan at matcher construction — a failure
downgrades the matcher to the two-program path (health-registry note).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from banjax_tpu.matcher import windows as W

_SHIFTS = (0, 8, 16, 24)


# ---- the Pallas window-scan kernel ----


def _scan_kernel(b_ref, gh_ref, gs_ref, gn_ref, gv_ref, ts_ref, tn_ref,
                 lim_ref, ivs_ref, ivn_ref, pad_ref,
                 h_out, s_out, n_out, mt_out, ex_out):
    """Sequential fixed-window recurrence over the key-sorted event list.

    All refs are [1, E] int32 in VMEM (E = max_events; ~16 KB per array,
    far under the VMEM budget, so the whole event tile is resident for
    the scan).  The recurrence is inherently serial — a window restart
    depends on every earlier event of the segment — so the loop carries
    the (hits, start_s, start_ns) triple exactly like the lax.scan; the
    body is windows._window_step itself, shared with the XLA path."""
    E = b_ref.shape[1]

    def body(k, carry):
        xs = (
            b_ref[0, k] != 0,
            gh_ref[0, k], gs_ref[0, k], gn_ref[0, k],
            gv_ref[0, k] != 0,
            ts_ref[0, k], tn_ref[0, k],
            lim_ref[0, k], ivs_ref[0, k], ivn_ref[0, k],
            pad_ref[0, k] != 0,
        )
        carry, (h2, s1, n1, mtype, exceeded) = W._window_step(carry, xs)
        h_out[0, k] = h2
        s_out[0, k] = s1
        n_out[0, k] = n1
        mt_out[0, k] = mtype
        ex_out[0, k] = exceeded.astype(jnp.int32)
        return carry

    jax.lax.fori_loop(
        0, E, body, (jnp.int32(0), jnp.int32(0), jnp.int32(0))
    )


@functools.lru_cache(maxsize=16)
def _scan_call(E: int, interpret: bool):
    shape = jax.ShapeDtypeStruct((1, E), jnp.int32)
    return pl.pallas_call(
        _scan_kernel,
        out_shape=(shape, shape, shape, shape, shape),
        interpret=interpret,
    )


def window_scan(interpret: bool):
    """A `scan_fn` for windows._apply_core: same contract as the
    lax.scan over _window_step (the recurrence always starts from the
    zero carry, so `init` is ignored), lowered through the Pallas
    kernel above."""

    def scan(init, xs):
        del init  # the recurrence starts from the zero carry
        E = int(xs[0].shape[0])
        call = _scan_call(E, bool(interpret))
        ins = tuple(
            jnp.asarray(x).astype(jnp.int32).reshape(1, E) for x in xs
        )
        h, s, n, mt, ex = call(*ins)
        return (
            h.reshape(E), s.reshape(E), n.reshape(E), mt.reshape(E),
            ex.reshape(E) != 0,
        )

    return scan


def scan_selftest(interpret: bool, E: int = 64) -> None:
    """Prove the active scan lowering (compiled Mosaic on TPU, interpret
    elsewhere) reproduces the lax.scan recurrence bit-for-bit on a
    deterministic stimulus covering boundaries, pads, restarts and
    exceeds.  Raises on a lowering failure or any value mismatch — the
    matcher then stays on the two-program path (graceful downgrade)."""
    rng = np.random.default_rng(7)
    pad = np.zeros(E, dtype=bool)
    pad[-max(1, E // 8):] = True
    xs = (
        jnp.asarray(rng.integers(0, 2, E).astype(bool)),     # boundary
        jnp.asarray(rng.integers(0, 6, E).astype(np.int32)),  # g_hits
        jnp.asarray(rng.integers(0, 40, E).astype(np.int32)),  # g_ss
        jnp.asarray(rng.integers(0, 1000, E).astype(np.int32)),  # g_sns
        jnp.asarray(rng.integers(0, 2, E).astype(bool)),     # g_valid
        jnp.asarray(rng.integers(0, 60, E).astype(np.int32)),  # e_ts_s
        jnp.asarray(rng.integers(0, 1000, E).astype(np.int32)),  # e_ts_ns
        jnp.asarray(rng.integers(0, 4, E).astype(np.int32)),  # limit
        jnp.asarray(rng.integers(1, 20, E).astype(np.int32)),  # iv_s
        jnp.asarray(rng.integers(0, 1000, E).astype(np.int32)),  # iv_ns
        jnp.asarray(pad),                                     # pad
    )
    init = (jnp.int32(0), jnp.int32(0), jnp.int32(0))
    _, want = jax.lax.scan(W._window_step, init, xs)
    got = window_scan(interpret)(init, xs)
    for name, w, g in zip(
        ("hits", "start_s", "start_ns", "match_type", "exceeded"), want, got
    ):
        if not np.array_equal(np.asarray(w), np.asarray(g)):
            raise RuntimeError(
                f"pallas window-scan selftest mismatch on {name!r}"
            )


# ---- the single fused program ----


def build_single_program(
    pf, windows, active_table, n_rules: int, Bp: int, L_p: int, *,
    f_idx, a_idx, aw, ae, scan_fn,
):
    """One jitted device program: match core + dense bitmap assembly +
    live mask + overflow/chain gate + window commit + compact output.

    Returns (fn, K, P) where
      fn(state, chain_ok, combined, n_real, host_idx, slots, ts_s,
         ts_ns, live) -> (new_state, chain_ok_out, buf, bits_dev)
    with `state` donated (the HBM-resident window arrays mutate in
    place) and `buf` the single uint8 pull:

      flags[4 × i32: ok, n_cand, n_pairs, n_events]
      ‖ (row, rule) pairs [4P]
      ‖ always-rule bits [Bp * na8]            (when the plan has any)
      ‖ ev line/rule/hits/start_s/start_ns [5 × 4E]
      ‖ ev match_type/exceeded/seen_ip [3 × E]

    The layouts of the head and the event tail are byte-identical to
    program A's and program B's buffers respectively, so the host decode
    is shared with the two-program path."""
    block, K = pf.capacities(Bp)
    core = pf._match_core(Bp, L_p, K, block)
    P = pf.pair_capacity(Bp, K)
    plan = pf.plan
    n_always = plan.n_always
    n_filt = plan.stage2.n_rules
    max_events = windows.max_events
    limits, iv_s, iv_ns = windows._limits, windows._iv_s, windows._iv_ns
    active_table = jnp.asarray(active_table)
    shifts = jnp.asarray(_SHIFTS, dtype=jnp.int32)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def single(state, chain_ok, combined, n_real, host_idx, slots,
               ts_s, ts_ns, live):
        c = core(combined)
        pairs, n_pairs, pair_bits = pf.pairs_from_core(c, K, P)
        # dense caller-order bitmap, assembled on device (as program A)
        m2 = pair_bits[:, :n_filt].astype(jnp.uint8)          # [K, n_filt]
        filt = jnp.zeros((Bp + 1, n_filt), dtype=jnp.uint8)
        filt = filt.at[c["idx_caller_k"]].set(m2)[:Bp]        # row Bp = dump
        bits = jnp.zeros((Bp, n_rules), dtype=jnp.uint8)
        bits = bits.at[:, f_idx].set(filt)
        ab = None
        if n_always:
            ab = c["ab_caller"] | aw[None, :]
            empty = (c["lens_raw"] == 0).astype(jnp.uint8)[:, None]
            ab = ab | (ae[None, :] * empty)
            bits = bits.at[:, a_idx].set(ab)
        real = jax.lax.iota(jnp.int32, Bp) < n_real
        bits = bits * real[:, None].astype(jnp.uint8)
        # the live mask composes staleness/abandon INTO the commit: a row
        # the caller dropped contributes no event and no state write (the
        # returned dense bitmap stays unmasked — the classic fallback
        # applies its own mask, exactly like the two-program path)
        bits_live = bits * live[:, None]
        fire = (bits_live != 0) & active_table[host_idx]
        n_events = fire.sum(dtype=jnp.int32)
        self_ok = (
            (c["n_cand"] <= K) & (n_pairs <= P) & (n_events <= max_events)
        )
        # chain gate: a gated predecessor (overflow anywhere earlier in
        # the submit chain) gates THIS commit too, keeping device apply
        # order == log order across the host's classic replays
        ok = self_ok & (chain_ok != 0)
        new_state, ev = W._apply_core(
            state, bits_live, active_table, host_idx, slots, ts_s, ts_ns,
            limits, iv_s, iv_ns, n_rules=n_rules, max_events=max_events,
            gate=ok, scan_fn=scan_fn,
        )
        flags = jnp.stack(
            [ok.astype(jnp.int32), c["n_cand"], n_pairs, n_events]
        )
        parts = [
            ((flags[:, None] >> shifts[None, :]) & 0xFF)
            .astype(jnp.uint8).reshape(-1),
            ((pairs[:, None] >> shifts[None, :]) & 0xFF)
            .astype(jnp.uint8).reshape(-1),
        ]
        if n_always:
            parts.append(
                jnp.packbits(ab.astype(jnp.bool_), axis=1).reshape(-1)
            )
        for key in ("line", "rule", "hits", "start_s", "start_ns"):
            parts.append(
                ((ev[key][:, None] >> shifts[None, :]) & 0xFF)
                .astype(jnp.uint8).reshape(-1)
            )
        parts.append(ev["match_type"].astype(jnp.uint8))
        parts.append(ev["exceeded"].astype(jnp.uint8))
        parts.append(ev["seen_ip"].astype(jnp.uint8))
        return new_state, ok.astype(jnp.int32), jnp.concatenate(parts), bits

    return single, K, P
