"""Batched sha256 PoW verification kernel (challenge plane, ROADMAP item 3).

The sha-inv challenge accepts a cookie when
``leading_zero_bits(sha256(hmac[20] || solution[32])) >= N``
(crypto/challenge.py:validate_sha_inv_cookie).  The hashed message is
always exactly 52 bytes, which pads to a SINGLE 64-byte SHA-256 block —
so a batch of B candidate solutions is one embarrassingly-parallel
[16, B] uint32 problem: each lane runs the 64-round compression from the
fixed IV and counts the digest's leading zero bits in-kernel, returning
one int32 per candidate.  No per-candidate host hashing, one dispatch
per micro-batch.

Layout follows fused_match_window.py: 2-D refs ([16, B] message words
in, [1, B] zero-bit counts out), batch padded to the 128-wide TPU lane
so every shape is static, and a cached pallas_call builder per (B,
interpret).  All arithmetic is uint32 with wrapping adds; rotr is the
two-shift form and clz is a portable bit-length cascade (no lax.clz —
see /opt/skills/guides/pallas_guide.md on lowering portability).

``pow_selftest`` proves the kernel against hashlib + the pure-Python
count_zero_bits_from_left before the verifier routes real traffic to
it; a selftest failure downgrades the verifier to the CPU oracle (the
scan_selftest pattern), never changing an accept/reject decision.
"""

from __future__ import annotations

import functools
import hashlib
import os
from typing import List, Sequence, Tuple

import numpy as np

# 52-byte message = hmac[20] || solution[32]; one padded SHA-256 block:
# 13 data words, 0x80 terminator word, zero word, 416-bit length word.
POW_MESSAGE_BYTES = 20 + 32
_PAD_WORD_80 = 0x80000000
_LEN_BITS = POW_MESSAGE_BYTES * 8
LANE = 128  # TPU lane width — batch dim padded to a multiple of this

_H0 = (0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
       0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19)

_K = (
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5,
    0x3956C25B, 0x59F111F1, 0x923F82A4, 0xAB1C5ED5,
    0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174,
    0xE49B69C1, 0xEFBE4786, 0x0FC19DC6, 0x240CA1CC,
    0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7,
    0xC6E00BF3, 0xD5A79147, 0x06CA6351, 0x14292967,
    0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85,
    0xA2BFE8A1, 0xA81A664B, 0xC24B8B70, 0xC76C51A3,
    0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5,
    0x391C0CB3, 0x4ED8AA4A, 0x5B9CCA4F, 0x682E6FF3,
    0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
)


def _rotr(x, n: int):
    import jax.numpy as jnp

    return (x >> jnp.uint32(n)) | (x << jnp.uint32(32 - n))


def _clz32(x):
    """Leading zeros of a [1, B] uint32 via a bit-length cascade."""
    import jax.numpy as jnp

    bl = jnp.zeros(x.shape, jnp.int32)
    y = x
    for shift in (16, 8, 4, 2, 1):
        cond = (y >> jnp.uint32(shift)) > jnp.uint32(0)
        bl = bl + jnp.where(cond, shift, 0).astype(jnp.int32)
        y = jnp.where(cond, y >> jnp.uint32(shift), y)
    bl = bl + (y > jnp.uint32(0)).astype(jnp.int32)
    return jnp.int32(32) - bl


def _pow_kernel(msg_ref, out_ref):
    import jax.numpy as jnp

    # rolling 16-word schedule keeps VMEM at 16 rows, not 64
    w = [msg_ref[i : i + 1, :] for i in range(16)]
    a, b, c, d, e, f, g, h = (jnp.full_like(w[0], jnp.uint32(v)) for v in _H0)
    for i in range(64):
        if i < 16:
            wi = w[i]
        else:
            w15 = w[(i - 15) % 16]
            w2 = w[(i - 2) % 16]
            s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> jnp.uint32(3))
            s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> jnp.uint32(10))
            wi = w[i % 16] + s0 + w[(i - 7) % 16] + s1
            w[i % 16] = wi
        ch = (e & f) ^ (~e & g)
        t1 = h + (_rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)) + ch \
            + jnp.uint32(_K[i]) + wi
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = (_rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)) + maj
        a, b, c, d, e, f, g, h = t1 + t2, a, b, c, d + t1, e, f, g

    digest = [x + jnp.uint32(v)
              for x, v in zip((a, b, c, d, e, f, g, h), _H0)]
    total = jnp.zeros(digest[0].shape, jnp.int32)
    live = jnp.ones(digest[0].shape, jnp.bool_)
    for word in digest:
        total = total + jnp.where(live, _clz32(word), 0)
        live = live & (word == jnp.uint32(0))
    out_ref[0:1, :] = total


@functools.lru_cache(maxsize=16)
def _pow_call(batch: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    return pl.pallas_call(
        _pow_kernel,
        out_shape=jax.ShapeDtypeStruct((1, batch), jnp.int32),
        interpret=interpret,
    )


def pack_pow_messages(payloads: Sequence[bytes]) -> Tuple[np.ndarray, int]:
    """[16, B_padded] uint32 big-endian message words for a batch of
    52-byte hmac||solution payloads; returns (words, real_count).
    Padding lanes hash a zero message — harmless, their counts are
    sliced off."""
    n = len(payloads)
    padded = max(LANE, -(-n // LANE) * LANE)
    words = np.zeros((16, padded), dtype=np.uint32)
    buf = np.zeros((padded, 64), dtype=np.uint8)
    for j, payload in enumerate(payloads):
        if len(payload) != POW_MESSAGE_BYTES:
            raise ValueError(
                f"payload {j}: want {POW_MESSAGE_BYTES} bytes, "
                f"got {len(payload)}"
            )
        buf[j, :POW_MESSAGE_BYTES] = np.frombuffer(payload, np.uint8)
    words[:, :] = (
        buf.reshape(padded, 16, 4)
        .astype(np.uint32)
        .transpose(1, 0, 2)
        @ np.asarray([1 << 24, 1 << 16, 1 << 8, 1], np.uint32)
    )
    words[13, :] = _PAD_WORD_80
    words[14, :] = 0
    words[15, :] = _LEN_BITS
    return words, n


def leading_zero_bits_batch(
    payloads: Sequence[bytes], interpret: bool = False
) -> np.ndarray:
    """Leading-zero-bit counts of sha256(payload) for each 52-byte
    payload, one kernel dispatch."""
    words, n = pack_pow_messages(payloads)
    import jax.numpy as jnp

    out = _pow_call(words.shape[1], bool(interpret))(jnp.asarray(words))
    return np.asarray(out)[0, :n]


def _default_interpret() -> bool:
    import jax

    if os.environ.get("BANJAX_POW_INTERPRET"):
        return True
    return jax.default_backend() == "cpu"


def pow_selftest(interpret: bool = None) -> None:
    """Differential proof vs hashlib before the kernel sees traffic.
    Raises RuntimeError on any mismatch; the verifier downgrades to the
    CPU oracle on failure (scan_selftest pattern)."""
    from banjax_tpu.crypto.challenge import count_zero_bits_from_left

    if interpret is None:
        interpret = _default_interpret()
    rng = np.random.default_rng(0x51A)
    payloads: List[bytes] = [
        rng.integers(0, 256, POW_MESSAGE_BYTES, np.uint8).tobytes()
        for _ in range(24)
    ]
    # force easy leading-zero structure into some lanes so the clz
    # cascade's word-boundary handling is actually exercised
    payloads.append(b"\x00" * POW_MESSAGE_BYTES)
    payloads.append(b"\x00" * 51 + b"\x01")
    got = leading_zero_bits_batch(payloads, interpret=interpret)
    for payload, bits in zip(payloads, got.tolist()):
        digest = hashlib.sha256(payload).digest()
        want = count_zero_bits_from_left(digest)
        if bits != want:
            raise RuntimeError(
                f"pow_verify selftest mismatch: payload "
                f"{payload[:8].hex()}… kernel={bits} hashlib={want}"
            )
