"""Pallas TPU kernel: batched bit-parallel NFA match.

This is the hand-scheduled version of banjax_tpu/matcher/nfa_jax.py — the
device replacement for the reference's serial per-(line, rule) regexp loop
(/root/reference/internal/regex_rate_limiter.go:216-269). The XLA scan in
nfa_jax is correct but its per-byte `jnp.take(b_table, cls)` gather is at
the mercy of XLA's gather lowering; this kernel instead

  * keeps the NFA state and the whole transition table resident in VMEM
    across the full byte scan — per block, HBM traffic is one read of the
    encoded lines and one write of the accept words, nothing else;
  * performs the byte-class → transition-mask gather as a one-hot matmul on
    the MXU: the uint32 table is split into four 8-bit planes stored as
    bf16, and `table[4W, C] @ onehot[C, block]` is exact because every
    one-hot column selects a single integer ≤ 255 (bf16 represents
    integers up to 256 exactly — 16-bit halves would NOT survive the
    MXU's single-pass bf16 mode). The gather rides the systolic array at
    full single-pass speed;
  * advances all rules at once with uint32 shift-and ops on the VPU.

Layout is TRANSPOSED versus nfa_jax: state is [W, block] — NFA words on
sublanes, lines on lanes. That makes the cross-word carry a sublane roll,
lets every mask slice be tiling-aligned (wps_p is a lane multiple), and
gives the per-byte column DMA a [8, block] tile. The byte position is the
innermost (sequential) grid axis: the Pallas pipeline double-buffers each
byte-row tile while the previous one computes; NFA state lives in VMEM
scratch across grid steps (reset at byte 0), accept bits accumulate into
the revisited output block.

Sharding: rule shards (rulec guarantees no branch straddles a shard
boundary) map to a grid axis — each (line-block, shard) pair scans an
independent word slab, so the same kernel serves the single-chip path and
the per-device body of the rp-sharded mesh path.

The `interpret=True` mode runs the identical kernel as plain JAX on CPU —
the CI path (SURVEY.md §4 carry-over (f)).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from banjax_tpu.matcher.rulec import CompiledRules

# mask-column indices in the packed [W, 8] uint32 mask tensor
_SHIFT_IN, _INJ_ALWAYS, _INJ_START, _SELFLOOP, _ACC_ANY, _ACC_END = range(6)

_LANE = 128            # TPU lane width
_SUBLANE = 8           # int32/f32 sublane tile
_COLS_PER_STEP = 8     # byte columns processed per grid step (one sublane tile)
_DEFAULT_BLOCK_B = 256
_MAX_WORDS_PER_SHARD = 2048  # VMEM guard: beyond this, fall back to nfa_jax


class PallasUnsupported(ValueError):
    """Ruleset shape the kernel refuses (caller falls back to nfa_jax)."""


@dataclasses.dataclass(frozen=True)
class PallasRules:
    """Kernel-ready repack of CompiledRules (padded, shard-major, transposed)."""

    n_rules: int
    n_shards: int
    wps: int             # original words per shard
    wps_p: int           # padded to a lane multiple
    n_classes_p: int     # padded to a lane multiple (it's the dot's lane axis)
    btab_t: jnp.ndarray  # [n_shards * 4 * wps_p, C_p] bf16 — 4 byte planes per shard
    masks_t: jnp.ndarray  # [n_shards * wps_p, 8] uint32
    # extraction arrays (word indices remapped into the padded word space)
    acc_word: jnp.ndarray     # [n_branches] int32
    acc_mask: jnp.ndarray     # [n_branches] uint32
    branch_rule: jnp.ndarray  # [n_branches] int32
    always_match: jnp.ndarray  # [n_rules] bool
    empty_only: jnp.ndarray    # [n_rules] bool
    # jitted device_matcher per (B, L_p, block_b, interpret) — a mutable
    # cache inside a frozen dataclass, keyed per ruleset by construction
    _fns: dict = dataclasses.field(default_factory=dict, compare=False, repr=False)

    @property
    def total_words(self) -> int:
        return self.n_shards * self.wps_p

    def jitted(self, B: int, L_p: int, block_b: int, interpret: bool,
               pack: bool = False):
        key = (B, L_p, block_b, interpret, pack)
        fn = self._fns.get(key)
        if fn is None:
            fn = jax.jit(device_matcher(self, B, L_p, block_b, interpret, pack))
            self._fns[key] = fn
        return fn


def _pad_to(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


def auto_shards(n_words: int, target_wps: int = 384) -> int:
    """Shard count that keeps each shard's word slab in VMEM comfortably.

    384 words (≈12k NFA positions) pads to a 512-word slab: the per-step
    transient `planes[4W, block]` stays ≈2 MB and the per-shard tables
    ≈1 MB, leaving headroom for double-buffered byte tiles at block=256.
    """
    return max(1, -(-n_words // target_wps))


def prepare(compiled: CompiledRules) -> PallasRules:
    """Repack a compiled ruleset for the kernel.

    Each shard's `wps` words are padded independently to a lane multiple so
    a grid step over shard j addresses a self-contained, aligned word slab;
    accept-word indices are remapped to match. Padding words carry all-zero
    masks, so any state bit shifted into them is annihilated by `& bmask`.
    """
    ns, wps = compiled.n_shards, compiled.words_per_shard
    wps_p = max(_LANE, _pad_to(wps, _LANE))
    if wps_p > _MAX_WORDS_PER_SHARD:
        raise PallasUnsupported(
            f"{wps_p} words/shard exceeds the VMEM budget "
            f"({_MAX_WORDS_PER_SHARD}); use more rule shards or nfa_jax"
        )
    C = compiled.n_classes
    C_p = max(_LANE, _pad_to(C, _LANE))

    btab_t = np.zeros((ns * 4 * wps_p, C_p), dtype=np.float32)
    masks_t = np.zeros((ns * wps_p, 8), dtype=np.uint32)
    b = compiled.b_table  # [C, ns * wps] uint32
    mask_rows = [
        compiled.shift_in, compiled.inject_always, compiled.inject_start,
        compiled.selfloop, compiled.accept_any, compiled.accept_end,
    ]
    for j in range(ns):
        sl = slice(j * wps, (j + 1) * wps)
        for plane in range(4):
            vals = ((b[:, sl] >> np.uint32(8 * plane)) & np.uint32(0xFF)).astype(
                np.float32
            )  # [C, wps]
            base = j * 4 * wps_p + plane * wps_p
            btab_t[base : base + wps, :C] = vals.T
        for r, row in enumerate(mask_rows):
            masks_t[j * wps_p : j * wps_p + wps, r] = row[sl]

    shard_of = compiled.acc_word // wps if compiled.acc_word.size else compiled.acc_word
    acc_word_p = (shard_of * wps_p + compiled.acc_word % wps).astype(np.int32)

    return PallasRules(
        n_rules=compiled.n_rules,
        n_shards=ns,
        wps=wps,
        wps_p=wps_p,
        n_classes_p=C_p,
        btab_t=jnp.asarray(btab_t, dtype=jnp.bfloat16),
        masks_t=jnp.asarray(masks_t),
        acc_word=jnp.asarray(acc_word_p),
        acc_mask=jnp.asarray(compiled.acc_mask),
        branch_rule=jnp.asarray(compiled.branch_rule),
        always_match=jnp.asarray(compiled.always_match),
        empty_only=jnp.asarray(compiled.empty_only),
    )


def _kernel(cls_rows_ref, lens_ref, btab_ref, masks_ref, out_ref, d_ref,
            *, C, W, use_roll):
    """One (line-block, rule-shard, byte-tile) grid step: 8 byte columns."""
    t = pl.program_id(2)
    bB = cls_rows_ref.shape[1]
    shift_in = masks_ref[:, _SHIFT_IN : _SHIFT_IN + 1]      # [W, 1]
    inj_always = masks_ref[:, _INJ_ALWAYS : _INJ_ALWAYS + 1]
    inj_start = masks_ref[:, _INJ_START : _INJ_START + 1]
    selfloop = masks_ref[:, _SELFLOOP : _SELFLOOP + 1]
    acc_any = masks_ref[:, _ACC_ANY : _ACC_ANY + 1]
    acc_end = masks_ref[:, _ACC_END : _ACC_END + 1]
    zero = jnp.uint32(0)

    @pl.when(t == 0)
    def _init():
        d_ref[:] = jnp.zeros((W, bB), dtype=jnp.uint32)
        out_ref[:] = jnp.zeros((W, bB), dtype=jnp.uint32)

    last_col = lens_ref[:] - 1  # [1, bB]
    cls_iota = jax.lax.broadcasted_iota(jnp.int32, (C, bB), 0)
    d = d_ref[:]
    acc = out_ref[:]
    for k in range(_COLS_PER_STEP):
        cls_row = cls_rows_ref[k : k + 1, :]                  # [1, bB]
        onehot = (cls_row == cls_iota).astype(jnp.bfloat16)   # [C, bB]
        # MXU gather: one-hot columns select byte values ≤ 255, exact in bf16
        planes = jnp.dot(btab_ref[:], onehot, preferred_element_type=jnp.float32)
        # Mosaic has no f32→u32 cast; values ≤ 255 so f32→i32→u32 is exact
        pi = planes.astype(jnp.int32).astype(jnp.uint32)      # [4W, bB]
        bmask = (
            pi[:W]
            | (pi[W : 2 * W] << 8)
            | (pi[2 * W : 3 * W] << 16)
            | (pi[3 * W :] << 24)
        )
        c31 = d >> 31
        if use_roll:
            sub0 = jax.lax.broadcasted_iota(jnp.int32, (W, bB), 0) == 0
            carry_bits = pltpu.roll(c31, shift=1, axis=0)
            carry_bits = jnp.where(sub0, zero, carry_bits)
        else:  # interpret mode: plain-JAX equivalent of the sublane roll
            carry_bits = jnp.concatenate(
                [jnp.zeros((1, bB), jnp.uint32), c31[:-1, :]], axis=0
            )
        shifted = ((d << 1) | carry_bits) & shift_in
        if k == 0:
            inject = jnp.where(t == 0, inj_always | inj_start, inj_always)
        else:
            inject = inj_always
        d = ((shifted | inject) & bmask) | (d & bmask & selfloop)
        acc = acc | (d & acc_any)
        l = t * _COLS_PER_STEP + k
        acc = acc | jnp.where(last_col == l, d & acc_end, zero)
    d_ref[:] = d
    out_ref[:] = acc


def device_matcher(prep: PallasRules, B: int, L_p: int,
                   block_b: int = _DEFAULT_BLOCK_B, interpret: bool = False,
                   pack: bool = False):
    """Build the traceable device step: fn(cls_t [L_p, B], lens [B]) →
    matched [B, n_rules] uint8 (or [B, ceil(n_rules/8)] bit-packed when
    `pack` — 8× less device→host traffic for the runner's bitmap pull).
    Composable inside an outer jit (the bench harness chains it; the
    runner jits it standalone)."""
    call = _build_raw_call(
        B, L_p, prep.n_classes_p, prep.n_shards, prep.wps_p, block_b, interpret
    )
    acc_word, acc_mask = prep.acc_word, prep.acc_mask
    branch_rule = prep.branch_rule
    always_match, empty_only = prep.always_match, prep.empty_only
    n_rules = prep.n_rules
    btab_t, masks_t = prep.btab_t, prep.masks_t

    def fn(cls_t, lens):
        acc_t = call(cls_t, lens[None, :], btab_t, masks_t)  # [ns*wps_p, B]
        acc = acc_t.T
        matched = jnp.zeros((B, n_rules), dtype=jnp.uint8)
        if acc_word.shape[0] > 0:
            sel = (acc[:, acc_word] & acc_mask) != 0
            matched = matched.at[:, branch_rule].max(sel.astype(jnp.uint8))
        matched = matched | always_match.astype(jnp.uint8)[None, :]
        empty = (lens == 0)[:, None]
        matched = matched | (
            empty_only.astype(jnp.uint8)[None, :] & empty.astype(jnp.uint8)
        )
        if pack:
            return jnp.packbits(matched.astype(jnp.bool_), axis=1)
        return matched

    return fn


@functools.lru_cache(maxsize=32)
def _build_raw_call(
    B: int, L_p: int, C: int, ns: int, wps_p: int, block_b: int, interpret: bool
):
    if B % block_b or L_p % _COLS_PER_STEP:
        # a floor-divided grid would silently skip the tail of the batch
        raise PallasUnsupported(
            f"B={B} must be a multiple of block_b={block_b} and "
            f"L_p={L_p} a multiple of {_COLS_PER_STEP} (pad first, "
            "as match_batch_pallas does)"
        )
    grid = (B // block_b, ns, L_p // _COLS_PER_STEP)
    kern = functools.partial(_kernel, C=C, W=wps_p, use_roll=not interpret)
    call = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            # cls transposed [L_p, B]: one sublane tile of byte rows per step
            pl.BlockSpec(
                (_COLS_PER_STEP, block_b), lambda i, j, t: (t, i),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((1, block_b), lambda i, j, t: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (4 * wps_p, C), lambda i, j, t: (j, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((wps_p, 8), lambda i, j, t: (j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (wps_p, block_b), lambda i, j, t: (j, i), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((ns * wps_p, B), jnp.uint32),
        scratch_shapes=[pltpu.VMEM((wps_p, block_b), jnp.uint32)],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * B * L_p * C * 4 * wps_p * ns,
            bytes_accessed=B * L_p * 4 + B * ns * wps_p * 4,
            transcendentals=0,
        ),
    )
    return call


def match_batch_pallas(
    prep: PallasRules,
    cls_ids,
    lens,
    *,
    block_b: int = _DEFAULT_BLOCK_B,
    interpret: bool = False,
    packed: bool = False,
) -> np.ndarray:
    """[B, L] encoded lines → [B, n_rules] uint8 match bits via the kernel
    (bit-packed along the rule axis when `packed`).

    Pads the batch up to a block multiple; semantics identical to
    nfa_jax.match_batch (differentially tested in tests/unit/test_nfa_pallas.py).
    """
    if not interpret and block_b % _LANE:
        raise PallasUnsupported(f"block_b {block_b} must be a multiple of {_LANE}")
    cls_ids = np.asarray(cls_ids, dtype=np.int32)
    lens = np.asarray(lens, dtype=np.int32)
    B, L = cls_ids.shape
    Bp = max(block_b, _pad_to(B, block_b))
    L_p = max(_COLS_PER_STEP, _pad_to(L, _COLS_PER_STEP))
    cls_t = np.zeros((L_p, Bp), dtype=np.int32)
    cls_t[:L, :B] = cls_ids.T
    if Bp != B:
        lens = np.pad(lens, (0, Bp - B))
    run = prep.jitted(Bp, L_p, block_b, interpret, packed)
    out = run(jnp.asarray(cls_t), jnp.asarray(lens))
    return np.asarray(out)[:B]
