"""Pallas TPU kernel: batched bit-parallel NFA match.

This is the hand-scheduled version of banjax_tpu/matcher/nfa_jax.py — the
device replacement for the reference's serial per-(line, rule) regexp loop
(/root/reference/internal/regex_rate_limiter.go:216-269). The XLA scan in
nfa_jax is correct but its per-byte `jnp.take(b_table, cls)` gather is at
the mercy of XLA's gather lowering; this kernel instead

  * keeps the NFA state and the whole transition table resident in VMEM
    across the full byte scan — per block, HBM traffic is one read of the
    encoded lines and one write of the accept words, nothing else;
  * performs the byte-class → transition-mask gather as a one-hot matmul on
    the MXU: the uint32 table is split into four 8-bit planes stored as
    int8 biased by -128 (so 0..255 fits the signed range), and
    `table[4W, C] @ onehot[C, block]` is exact because every one-hot
    column selects a single row value; the +128 bias is added back on the
    VPU during plane recombination. int8 runs the MXU at twice the bf16
    rate (measured 2.0x on v5e);
  * skips byte tiles entirely once every line in the block has ended: the
    per-block tile count is a scalar-prefetch operand, so with
    length-sorted batches (match_batch_pallas sorts internally) short
    blocks run only the tiles they need instead of the padded maximum;
  * advances all rules at once with uint32 shift-and ops on the VPU.

Layout is TRANSPOSED versus nfa_jax: state is [W, block] — NFA words on
sublanes, lines on lanes. That makes the cross-word carry a sublane roll,
lets every mask slice be tiling-aligned (wps_p is a KERNEL_WORD_ALIGN = 32
multiple: the int8 sublane tile, which every in-kernel slice satisfies),
and gives the per-byte column DMA a [cols, block] tile. The byte position is the
innermost (sequential) grid axis: the Pallas pipeline double-buffers each
byte-row tile while the previous one computes; NFA state lives in VMEM
scratch across grid steps (reset at byte 0), accept bits accumulate into
the revisited output block.

Sharding: rule shards (rulec guarantees no branch straddles a shard
boundary) map to a grid axis — each (line-block, shard) pair scans an
independent word slab, so the same kernel serves the single-chip path and
the per-device body of the rp-sharded mesh path.

The `interpret=True` mode runs the identical kernel as plain JAX on CPU —
the CI path (SURVEY.md §4 carry-over (f)).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from banjax_tpu.matcher.rulec import KERNEL_WORD_ALIGN, CompiledRules

# mask-column indices in the packed [W, 8] uint32 mask tensor
_SHIFT_IN, _INJ_ALWAYS, _INJ_START, _SELFLOOP, _ACC_ANY, _ACC_END = range(6)

_LANE = 128            # TPU lane width
_SUBLANE = 8           # int32/f32 sublane tile
_COLS_PER_STEP = 8     # byte columns processed per grid step (one sublane tile)
_DEFAULT_BLOCK_B = 256
_MAX_WORDS_PER_SHARD = 2048  # VMEM guard: beyond this, fall back to nfa_jax


class PallasUnsupported(ValueError):
    """Ruleset shape the kernel refuses (caller falls back to nfa_jax)."""


@dataclasses.dataclass(frozen=True)
class PallasRules:
    """Kernel-ready repack of CompiledRules (padded, shard-major, transposed)."""

    n_rules: int
    n_shards: int
    wps: int             # original words per shard
    wps_p: int           # padded to a KERNEL_WORD_ALIGN (32) multiple
    n_classes_p: int     # padded to a lane multiple (it's the dot's lane axis)
    btab_t: jnp.ndarray  # [n_shards * 4 * wps_p, C_p] int8 — 4 byte planes, biased -128
    masks_t: jnp.ndarray  # [n_shards * wps_p, 8] uint32
    # extraction arrays (word indices remapped into the padded word space)
    acc_word: jnp.ndarray     # [n_branches] int32
    acc_mask: jnp.ndarray     # [n_branches] uint32
    branch_rule: jnp.ndarray  # [n_branches] int32
    always_match: jnp.ndarray  # [n_rules] bool
    empty_only: jnp.ndarray    # [n_rules] bool
    # carry_free (see prepare()): word-aligned branches let the kernel drop
    # the cross-word carry — 3 of ~13 VPU ops per byte column
    carry_free: bool = False
    # jitted device_matcher per (B, L_p, block_b, interpret) — a mutable
    # cache inside a frozen dataclass, keyed per ruleset by construction
    _fns: dict = dataclasses.field(default_factory=dict, compare=False, repr=False)

    @property
    def total_words(self) -> int:
        return self.n_shards * self.wps_p

    def jitted(self, B: int, L_p: int, block_b: int, interpret: bool,
               pack: bool = False, cols: int = _COLS_PER_STEP):
        key = (B, L_p, block_b, interpret, pack, cols)
        fn = self._fns.get(key)
        if fn is None:
            fn = jax.jit(
                device_matcher(self, B, L_p, block_b, interpret, pack, cols)
            )
            self._fns[key] = fn
        return fn


def _pad_to(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


def auto_shards(n_words: int, max_wps: int = 512) -> int:
    """Shard count minimizing total padded words (the dot's row axis).

    Each shard's word slab pads up to a KERNEL_WORD_ALIGN multiple, so the
    FLOP cost is `n_shards * pad(ceil(n_words / n_shards), align)`. Ties
    break toward fewer shards (fewer grid steps). `max_wps` caps the slab
    so the per-step VMEM transients stay comfortable at block=256.
    """
    if n_words <= 0:
        return 1
    best, best_cost = 1, None
    for ns in range(1, max(2, -(-n_words // 64)) + 1):
        # 4% slack over the even split: rulec's branch-atomic greedy packing
        # can overfill the fullest shard slightly beyond ceil(n_words / ns)
        wps_est = -(-n_words * 26 // (25 * ns))
        wps_p = max(KERNEL_WORD_ALIGN, _pad_to(wps_est, KERNEL_WORD_ALIGN))
        if wps_p > max_wps:
            continue
        cost = ns * wps_p
        if best_cost is None or cost < best_cost:
            best, best_cost = ns, cost
    return best


def prepare(compiled: CompiledRules) -> PallasRules:
    """Repack a compiled ruleset for the kernel.

    Each shard's `wps` words are padded independently to a KERNEL_WORD_ALIGN multiple so
    a grid step over shard j addresses a self-contained, aligned word slab;
    accept-word indices are remapped to match. Padding words carry all-zero
    masks, so any state bit shifted into them is annihilated by `& bmask`.

    (Accept-absorption was tried and REVERTED: making accept-any bits'
    b_table rows class-independent persists accepted bits, but it also
    lets the shift-in ENTER the accept position without checking the byte
    — a prefix of a literal followed by any pad byte falsely accepts.
    Separating "enter" from "persist" costs the same 2 VPU ops the trick
    would save, so the per-column accumulation stays.)
    """
    ns, wps = compiled.n_shards, compiled.words_per_shard
    # pad each slab to the int8 sublane tile (32), not the full lane (128):
    # every in-kernel slice stays tiling-aligned (btab plane slices at
    # multiples of W with 4W a 128-multiple; [W, 8] mask rows and the
    # [W, block] state need only 8) and the VPU scan — the measured
    # critical path — runs 4x fewer word rows for a ~40-word stage-1
    # automaton. BANJAX_NFA_WORD_ALIGN=128 restores the conservative pad.
    wps_p = max(KERNEL_WORD_ALIGN, _pad_to(wps, KERNEL_WORD_ALIGN))
    if wps_p > _MAX_WORDS_PER_SHARD:
        raise PallasUnsupported(
            f"{wps_p} words/shard exceeds the VMEM budget "
            f"({_MAX_WORDS_PER_SHARD}); use more rule shards or nfa_jax"
        )
    C = compiled.n_classes
    C_p = max(_LANE, _pad_to(C, _LANE))

    # int8 planes biased by -128: row value v is stored as v-128, and the
    # kernel adds the bias back after the dot (every one-hot column selects
    # exactly one row, including pad columns, which select the all-zero
    # class-0 row stored as -128).
    btab_t = np.full((ns * 4 * wps_p, C_p), -128, dtype=np.int16)
    masks_t = np.zeros((ns * wps_p, 8), dtype=np.uint32)
    b = compiled.b_table  # [C, ns * wps] uint32
    mask_rows = [
        compiled.shift_in, compiled.inject_always, compiled.inject_start,
        compiled.selfloop, compiled.accept_any, compiled.accept_end,
    ]
    for j in range(ns):
        sl = slice(j * wps, (j + 1) * wps)
        for plane in range(4):
            vals = ((b[:, sl] >> np.uint32(8 * plane)) & np.uint32(0xFF)).astype(
                np.int16
            )  # [C, wps]
            base = j * 4 * wps_p + plane * wps_p
            btab_t[base : base + wps, :C] = vals.T - 128
        for r, row in enumerate(mask_rows):
            masks_t[j * wps_p : j * wps_p + wps, r] = row[sl]

    shard_of = compiled.acc_word // wps if compiled.acc_word.size else compiled.acc_word
    acc_word_p = (shard_of * wps_p + compiled.acc_word % wps).astype(np.int32)

    return PallasRules(
        n_rules=compiled.n_rules,
        n_shards=ns,
        wps=wps,
        wps_p=wps_p,
        n_classes_p=C_p,
        btab_t=jnp.asarray(btab_t, dtype=jnp.int8),
        masks_t=jnp.asarray(masks_t),
        acc_word=jnp.asarray(acc_word_p),
        acc_mask=jnp.asarray(compiled.acc_mask),
        branch_rule=jnp.asarray(compiled.branch_rule),
        always_match=jnp.asarray(compiled.always_match),
        empty_only=jnp.asarray(compiled.empty_only),
        carry_free=compiled.carry_free,
    )


def _kernel(maxtile_ref, cls_rows_ref, lens_ref, btab_ref, masks_ref,
            out_ref, d_ref, *, C, W, use_roll, cols, carry=True):
    """One (line-block, rule-shard, byte-tile) grid step: `cols` byte columns."""
    i = pl.program_id(0)
    t = pl.program_id(2)
    bB = cls_rows_ref.shape[1]
    zero = jnp.uint32(0)

    @pl.when(t == 0)
    def _init():
        d_ref[:] = jnp.zeros((W, bB), dtype=jnp.uint32)
        out_ref[:] = jnp.zeros((W, bB), dtype=jnp.uint32)

    # Once every line in this block has ended, the remaining byte columns
    # are all pad (class 0, all-zero masks): state would only collapse, so
    # skipping the tile outright is exact.
    @pl.when(t < maxtile_ref[i])
    def _body():
        shift_in = masks_ref[:, _SHIFT_IN : _SHIFT_IN + 1]      # [W, 1]
        inj_always = masks_ref[:, _INJ_ALWAYS : _INJ_ALWAYS + 1]
        inj_start = masks_ref[:, _INJ_START : _INJ_START + 1]
        selfloop = masks_ref[:, _SELFLOOP : _SELFLOOP + 1]
        acc_any = masks_ref[:, _ACC_ANY : _ACC_ANY + 1]
        acc_end = masks_ref[:, _ACC_END : _ACC_END + 1]

        last_col = lens_ref[:] - 1  # [1, bB]
        cls_iota = jax.lax.broadcasted_iota(jnp.int32, (C, bB), 0)
        d = d_ref[:]
        acc = out_ref[:]
        for k in range(cols):
            cls_row = cls_rows_ref[k : k + 1, :]                # [1, bB]
            onehot = (cls_row == cls_iota).astype(jnp.int8)     # [C, bB]
            # MXU gather at the int8 rate: each one-hot column selects one
            # biased row value v-128; +128 restores the exact byte plane.
            # One dot per 8-bit plane keeps the int32 transient at [W, bB]
            # (a single [4W, C] dot would transiently hold 4x that in VMEM,
            # which caps block_b at small sizes).
            # Recombine biased planes in wrapping int32 arithmetic: mod 2^32,
            # Σ (v_k - 128) << 8k  =  (Σ v_k << 8k) - 0x80808080, so adding
            # 0x80808080 back yields exactly the OR of the unbiased byte
            # planes (they occupy disjoint bit lanes).
            s = None
            for plane in range(4):
                p = jax.lax.dot_general(
                    btab_ref[plane * W : (plane + 1) * W, :], onehot,
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32,
                )  # [W, bB] values in [-128, 127]
                p = p << (8 * plane) if plane else p
                s = p if s is None else s + p
            bmask = (s + jnp.int32(-0x7F7F7F80)).astype(jnp.uint32)
            if carry:
                c31 = d >> 31
                if use_roll:
                    sub0 = jax.lax.broadcasted_iota(jnp.int32, (W, bB), 0) == 0
                    carry_bits = pltpu.roll(c31, shift=1, axis=0)
                    carry_bits = jnp.where(sub0, zero, carry_bits)
                else:  # interpret mode: plain-JAX equivalent of the sublane roll
                    carry_bits = jnp.concatenate(
                        [jnp.zeros((1, bB), jnp.uint32), c31[:-1, :]], axis=0
                    )
                shifted = ((d << 1) | carry_bits) & shift_in
            else:
                # carry-free packing: no branch straddles a word, so the
                # shifted-out bit 31 could only land on a branch-start or
                # padding bit, both outside shift_in — drop the whole carry
                shifted = (d << 1) & shift_in
            if k == 0:
                inject = jnp.where(t == 0, inj_always | inj_start, inj_always)
            else:
                inject = inj_always
            d = ((shifted | inject) | (d & selfloop)) & bmask
            acc = acc | (d & acc_any)
            l = t * cols + k
            acc = acc | jnp.where(last_col == l, d & acc_end, zero)
        d_ref[:] = d
        out_ref[:] = acc


def device_matcher(prep: PallasRules, B: int, L_p: int,
                   block_b: int = _DEFAULT_BLOCK_B, interpret: bool = False,
                   pack: bool = False, cols: int = _COLS_PER_STEP):
    """Build the traceable device step: fn(cls_t [L_p, B], lens [B]) →
    matched [B, n_rules] uint8 (or [B, ceil(n_rules/8)] bit-packed when
    `pack` — 8× less device→host traffic for the runner's bitmap pull).
    Composable inside an outer jit (the bench harness chains it; the
    runner jits it standalone). `cols` = byte columns per grid step:
    wider tiles amortize the per-step Mosaic overhead (measured ~10-15µs
    per step on v5e) at the cost of L_p padding up to a `cols` multiple."""
    call = _build_raw_call(
        B, L_p, prep.n_classes_p, prep.n_shards, prep.wps_p, block_b,
        interpret, cols, carry=not prep.carry_free,
    )
    acc_word, acc_mask = prep.acc_word, prep.acc_mask
    branch_rule = prep.branch_rule
    always_match, empty_only = prep.always_match, prep.empty_only
    n_rules = prep.n_rules
    btab_t, masks_t = prep.btab_t, prep.masks_t

    def fn(cls_t, lens):
        # per-line-block byte-tile counts for the kernel's tile skip
        maxtile = jnp.asarray(
            -(-lens.reshape(B // block_b, block_b).max(axis=1) // cols),
            dtype=jnp.int32,
        )
        acc_t = call(maxtile, cls_t, lens[None, :], btab_t, masks_t)  # [ns*wps_p, B]
        acc = acc_t.T
        matched = jnp.zeros((B, n_rules), dtype=jnp.uint8)
        if acc_word.shape[0] > 0:
            sel = (acc[:, acc_word] & acc_mask) != 0
            matched = matched.at[:, branch_rule].max(sel.astype(jnp.uint8))
        matched = matched | always_match.astype(jnp.uint8)[None, :]
        empty = (lens == 0)[:, None]
        matched = matched | (
            empty_only.astype(jnp.uint8)[None, :] & empty.astype(jnp.uint8)
        )
        if pack:
            return jnp.packbits(matched.astype(jnp.bool_), axis=1)
        return matched

    return fn


@functools.lru_cache(maxsize=64)
def _build_raw_call(
    B: int, L_p: int, C: int, ns: int, wps_p: int, block_b: int,
    interpret: bool, cols: int = _COLS_PER_STEP,
    force_roll: "bool | None" = None,
    carry: bool = True,
):
    """`carry=False` is only sound against tensors packed word-aligned
    (prepare() reported carry_free) — pass prep's own flag. The safe
    default (carry on) is merely redundant work against aligned tensors,
    never wrong."""
    if B % block_b or L_p % cols:
        # a floor-divided grid would silently skip the tail of the batch
        raise PallasUnsupported(
            f"B={B} must be a multiple of block_b={block_b} and "
            f"L_p={L_p} a multiple of cols={cols} (pad first, "
            "as match_batch_pallas does)"
        )
    grid = (B // block_b, ns, L_p // cols)
    # the pltpu.roll carry is what production (compiled Mosaic) runs; it
    # also works under interpret, which is how CI covers the exact
    # production branch (tests/unit/test_nfa_pallas.py::test_roll_branch) —
    # the concatenate fallback stays for interpreters where roll regresses
    use_roll = (not interpret) if force_roll is None else force_roll
    kern = functools.partial(
        _kernel, C=C, W=wps_p, use_roll=use_roll, cols=cols, carry=carry,
    )
    call = pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,  # maxtile [B // block_b] int32
            grid=grid,
            in_specs=[
                # cls transposed [L_p, B]: one tile of byte rows per step
                pl.BlockSpec(
                    (cols, block_b), lambda i, j, t, mt: (t, i),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, block_b), lambda i, j, t, mt: (0, i),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (4 * wps_p, C), lambda i, j, t, mt: (j, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (wps_p, 8), lambda i, j, t, mt: (j, 0),
                    memory_space=pltpu.VMEM,
                ),
            ],
            out_specs=pl.BlockSpec(
                (wps_p, block_b), lambda i, j, t, mt: (j, i),
                memory_space=pltpu.VMEM,
            ),
            scratch_shapes=[pltpu.VMEM((wps_p, block_b), jnp.uint32)],
        ),
        out_shape=jax.ShapeDtypeStruct((ns * wps_p, B), jnp.uint32),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * B * L_p * C * 4 * wps_p * ns,
            bytes_accessed=B * L_p * 4 + B * ns * wps_p * 4,
            transcendentals=0,
        ),
    )
    return call


def match_batch_pallas(
    prep: PallasRules,
    cls_ids,
    lens,
    *,
    block_b: int = _DEFAULT_BLOCK_B,
    interpret: bool = False,
    packed: bool = False,
    cols: int = _COLS_PER_STEP,
) -> np.ndarray:
    """[B, L] encoded lines → [B, n_rules] uint8 match bits via the kernel
    (bit-packed along the rule axis when `packed`).

    Pads the batch up to a block multiple and sorts lines by length so the
    kernel's per-block tile skip pays off (the output is returned in the
    caller's original line order); semantics identical to
    nfa_jax.match_batch (differentially tested in tests/unit/test_nfa_pallas.py).
    """
    if not interpret and block_b % _LANE:
        raise PallasUnsupported(f"block_b {block_b} must be a multiple of {_LANE}")
    cls_ids = np.asarray(cls_ids, dtype=np.int32)
    lens = np.asarray(lens, dtype=np.int32)
    B, L = cls_ids.shape
    order = np.argsort(lens, kind="stable")
    Bp = max(block_b, _pad_to(B, block_b))
    # trim the scan to the batch's longest line (columns past every line's
    # end are pad-class and can't change state), rounded to a multiple of
    # 32 so the number of jitted L_p variants stays small
    max_len = int(lens.max()) if B else 0
    round_to = max(32, cols)
    L_p = max(cols, min(_pad_to(L, cols), _pad_to(max_len, round_to)))
    cls_t = np.zeros((L_p, Bp), dtype=np.int32)
    cls_t[: min(L, L_p), :B] = cls_ids[order, : min(L, L_p)].T
    lens_sorted = lens[order]
    if Bp != B:
        lens_sorted = np.pad(lens_sorted, (0, Bp - B))
    run = prep.jitted(Bp, L_p, block_b, interpret, packed, cols)
    out = np.asarray(run(jnp.asarray(cls_t), jnp.asarray(lens_sorted)))[:B]
    unsorted = np.empty_like(out)
    unsorted[order] = out
    return unsorted
