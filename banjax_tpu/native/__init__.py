"""Native (C) batch parse+encode for the tailer hot path.

Loads fastparse.c as a ctypes shared library, compiling it with the system
C compiler on first use (cached beside the source; no pybind11/setuptools
needed). If no compiler is available the module degrades to None and the
callers keep the pure-Python path — semantics are identical either way
(the C side defers any line it cannot prove it parses identically).

This is the framework's native runtime tier for host-side IO (the Pallas
kernel being the device tier): at the 5M lines/s north star the Python
per-line parse loop is the host bottleneck; this runs it at memory speed.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import sysconfig
import tempfile
import threading
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)

FLAG_ERROR = 1
FLAG_OLD = 2
FLAG_DEFER = 4
FLAG_HOST_EVAL = 8

_SRC = os.path.join(os.path.dirname(__file__), "fastparse.c")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _so_path() -> str:
    plat = sysconfig.get_platform().replace("-", "_")
    cache_dir = os.environ.get(
        "BANJAX_NATIVE_CACHE", os.path.join(tempfile.gettempdir(), "banjax-native")
    )
    os.makedirs(cache_dir, exist_ok=True)
    src_mtime = int(os.stat(_SRC).st_mtime)
    return os.path.join(cache_dir, f"fastparse_{plat}_{src_mtime}.so")


def _compile(so: str) -> bool:
    for cc in (os.environ.get("CC"), "cc", "gcc", "clang"):
        if not cc:
            continue
        cmd = [cc, "-O3", "-shared", "-fPIC", "-o", so, _SRC, "-lm"]
        try:
            r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired):
            continue
        if r.returncode == 0:
            return True
        log.debug("native compile with %s failed: %s", cc, r.stderr[-500:])
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("BANJAX_NO_NATIVE"):
            return None
        so = _so_path()
        if not os.path.exists(so) and not _compile(so):
            log.info("no C compiler available; using the Python parse path")
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError as e:
            log.warning("could not load %s: %s", so, e)
            return None
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.fp_split_lines.restype = ctypes.c_int64
        lib.fp_split_lines.argtypes = [u8p, ctypes.c_int64, i64p, i64p, ctypes.c_int64]
        lib.fp_parse_encode.restype = ctypes.c_int64
        lib.fp_parse_encode.argtypes = [
            u8p, ctypes.c_int64, i64p, i64p, ctypes.c_int64,
            i32p, ctypes.c_int32, ctypes.c_double, ctypes.c_double,
            i64p, u8p, i64p, i32p, i64p, i32p, i64p, i32p, i32p, i32p,
        ]
        lib.fp_dedup_spans.restype = ctypes.c_int64
        lib.fp_dedup_spans.argtypes = [
            u8p, ctypes.c_int64, i64p, i32p, ctypes.c_int64,
            i64p, ctypes.c_int64, i64p, i64p,
        ]
        _LIB = lib
        log.info("native fastparse loaded (%s)", so)
        return _LIB


def available() -> bool:
    return _load() is not None


class ParsedBatch:
    """Column-oriented result of one native parse+encode pass.

    String fields stay as (offset, length) spans into the blob; `.ip(i)`,
    `.host(i)`, `.rest(i)` materialize Python strings lazily — most lines
    only ever need ip/host (allowlist + active-table lookups)."""

    __slots__ = (
        "blob", "n", "ts_ns", "flags", "ip_off", "ip_len",
        "host_off", "host_len", "rest_off", "rest_len", "cls_ids", "lens",
        "_text",
    )

    def __init__(self, blob, n, ts_ns, flags, ip_off, ip_len, host_off,
                 host_len, rest_off, rest_len, cls_ids, lens):
        self.blob = blob
        self.n = n
        self.ts_ns = ts_ns
        self.flags = flags
        self.ip_off, self.ip_len = ip_off, ip_len
        self.host_off, self.host_len = host_off, host_len
        self.rest_off, self.rest_len = rest_off, rest_len
        self.cls_ids = cls_ids
        self.lens = lens
        self._text = False  # False = not computed; None = non-ascii blob

    def text(self):
        """The whole blob as ONE str when it is pure ASCII (byte offsets
        == str offsets, so span strings are plain slices — ~10x cheaper
        than per-span bytes.decode), else None. Decoded once, cached."""
        if self._text is False:
            self._text = (
                self.blob.decode("ascii") if self.blob.isascii() else None
            )
        return self._text

    def _span(self, off, ln, i) -> str:
        o = int(off[i])
        return self.blob[o : o + int(ln[i])].decode("utf-8", "surrogatepass")

    def ip(self, i: int) -> str:
        return self._span(self.ip_off, self.ip_len, i)

    def host(self, i: int) -> str:
        return self._span(self.host_off, self.host_len, i)

    def rest(self, i: int) -> str:
        return self._span(self.rest_off, self.rest_len, i)


class ParseScratch:
    """Reusable output buffers for parse_encode_batch.

    Fresh numpy allocations cost ~15 ms in page faults per 65k-line batch
    (the [n, max_len] int32 class matrix alone is 33 MB); a caller that
    parses batch after batch should own ONE scratch and pass it in. The
    returned ParsedBatch views alias the scratch — they are valid until
    the next parse_encode_batch call with the same scratch (the TpuMatcher
    consumes each batch fully before parsing the next)."""

    def __init__(self):
        self.cap = 0
        self.max_len = 0

    def ensure(self, n: int, max_len: int) -> None:
        if n <= self.cap and max_len == self.max_len:
            return
        cap = max(n, self.cap, 1024)
        self.cap, self.max_len = cap, max_len
        self.starts = np.empty(cap, dtype=np.int64)
        self.ends = np.empty(cap, dtype=np.int64)
        self.ts_ns = np.empty(cap, dtype=np.int64)
        self.flags = np.empty(cap, dtype=np.uint8)
        self.ip_off = np.empty(cap, dtype=np.int64)
        self.ip_len = np.empty(cap, dtype=np.int32)
        self.host_off = np.empty(cap, dtype=np.int64)
        self.host_len = np.empty(cap, dtype=np.int32)
        self.rest_off = np.empty(cap, dtype=np.int64)
        self.rest_len = np.empty(cap, dtype=np.int32)
        self.cls_ids = np.empty((cap, max_len), dtype=np.int32)
        self.lens = np.empty(cap, dtype=np.int32)


# parse threads: fp_parse_encode is row-parallel and ctypes releases the
# GIL, so splitting the row range across a few threads scales the 14.5 ms
# (65k lines) C pass down to ~4-7 ms
_PARSE_THREADS = min(4, os.cpu_count() or 1)
_MIN_ROWS_PER_THREAD = 4096


def parse_encode_batch(
    lines, byte_to_class: np.ndarray, max_len: int,
    now_unix: float, old_cutoff: float,
    scratch: Optional[ParseScratch] = None,
    max_threads: Optional[int] = None,
) -> Optional[ParsedBatch]:
    """One native pass over a batch of log lines; None if the native
    library is unavailable (caller uses the Python path). With `scratch`,
    outputs alias the caller-owned buffers (see ParseScratch).
    `max_threads` caps the internal row-parallel fan-out — callers that
    are themselves one shard of a worker pool (the pipeline's sharded
    encode) pass 1 so the pool's parallelism isn't multiplied."""
    lib = _load()
    if lib is None:
        return None
    blob = "\n".join(lines).encode("utf-8", "surrogatepass")
    n = len(lines)
    buf = np.frombuffer(blob, dtype=np.uint8)
    if n == 0:
        empty64 = np.zeros(0, dtype=np.int64)
        empty32 = np.zeros(0, dtype=np.int32)
        return ParsedBatch(blob, 0, empty64, np.zeros(0, np.uint8), empty64,
                           empty32, empty64, empty32, empty64, empty32,
                           np.zeros((0, max_len), np.int32), empty32)

    s = scratch if scratch is not None else ParseScratch()
    s.ensure(n, max_len)
    starts, ends = s.starts[:n], s.ends[:n]
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)

    def P(a, t):
        return a.ctypes.data_as(t)

    blob_ptr = buf.ctypes.data_as(u8p) if buf.size else ctypes.cast(
        ctypes.c_char_p(b""), u8p
    )
    got = lib.fp_split_lines(blob_ptr, len(blob), P(starts, i64p), P(ends, i64p), n)
    # embedded newline inside a "line" (callers pass tailer lines, which
    # cannot contain one) would shift every subsequent span: fall back
    # rather than misattribute. Detection rides the split itself (no extra
    # blob scan): extra newlines make the capped split stop short of the
    # blob end (or, for a trailing newline, return n-1 lines).
    if got != n or int(ends[n - 1]) != len(blob):
        return None

    table = np.ascontiguousarray(byte_to_class[:256], dtype=np.int32)

    def run_range(i0: int, cnt: int) -> None:
        lib.fp_parse_encode(
            blob_ptr, len(blob),
            P(s.starts[i0:], i64p), P(s.ends[i0:], i64p), cnt,
            P(table, i32p), max_len, now_unix, old_cutoff,
            P(s.ts_ns[i0:], i64p), P(s.flags[i0:], u8p),
            P(s.ip_off[i0:], i64p), P(s.ip_len[i0:], i32p),
            P(s.host_off[i0:], i64p), P(s.host_len[i0:], i32p),
            P(s.rest_off[i0:], i64p), P(s.rest_len[i0:], i32p),
            P(s.cls_ids[i0:], i32p), P(s.lens[i0:], i32p),
        )

    limit = _PARSE_THREADS if max_threads is None else max(1, max_threads)
    nt = min(limit, max(1, n // _MIN_ROWS_PER_THREAD))
    if nt <= 1:
        run_range(0, n)
    else:
        bounds = [n * t // nt for t in range(nt + 1)]
        threads = [
            threading.Thread(
                target=run_range, args=(bounds[t], bounds[t + 1] - bounds[t])
            )
            for t in range(1, nt)
        ]
        for t in threads:
            t.start()
        run_range(bounds[0], bounds[1] - bounds[0])
        for t in threads:
            t.join()

    return ParsedBatch(blob, n, s.ts_ns[:n], s.flags[:n], s.ip_off[:n],
                       s.ip_len[:n], s.host_off[:n], s.host_len[:n],
                       s.rest_off[:n], s.rest_len[:n], s.cls_ids[:n],
                       s.lens[:n])


class DedupScratch:
    """Reusable hash-table + output buffers for dedup_spans."""

    def __init__(self):
        self.cap = 0

    def ensure(self, n: int) -> None:
        if n <= self.cap:
            return
        cap = max(n, 1024)
        self.cap = cap
        tcap = 1
        while tcap < 2 * cap:
            tcap <<= 1
        self.table = np.empty(tcap, dtype=np.int64)
        self.ids = np.empty(cap, dtype=np.int64)
        self.first = np.empty(cap, dtype=np.int64)


def dedup_spans(blob, offs, lens, scratch=None):
    """(ids[n] first-appearance-ordered, first_rows[n_uniq]) for byte
    spans of `blob` — C open-addressing dedup; None when the native
    library is unavailable (caller falls back to the numpy path)."""
    lib = _load()
    if lib is None:
        return None
    n = len(offs)
    s = scratch if scratch is not None else DedupScratch()
    s.ensure(n)
    if n == 0:
        return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.int64)
    buf = np.frombuffer(blob, dtype=np.uint8)
    offs = np.ascontiguousarray(offs, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    tcap = len(s.table)
    n_uniq = lib.fp_dedup_spans(
        buf.ctypes.data_as(u8p), len(blob),
        offs.ctypes.data_as(i64p), lens.ctypes.data_as(i32p), n,
        s.table.ctypes.data_as(i64p), tcap,
        s.ids.ctypes.data_as(i64p), s.first.ctypes.data_as(i64p),
    )
    # copies, NOT views: a second dedup with the same scratch (the gate
    # runs ip then host spans back to back) must not clobber the first
    # call's result
    return s.ids[:n].copy(), s.first[:n_uniq].copy()
