/* Shared-memory decision table: the compiled /auth_request fast path.
 *
 * A shm-resident, open-addressed table of already-decided IPs — the
 * kernel-adjacent twin of the reference escalating decided IPs out of
 * userspace into ipset entries with per-entry timeouts.  Every fastserve
 * worker maps the same segment; the primary's DynamicDecisionLists
 * mirrors every insert/expiry/removal into it, and the HTTP fast path
 * answers a hit with one probe instead of the Python decision chain.
 *
 * Layout: one 128-byte header then capacity (power of two) 96-byte
 * slots.  Linear probing bounded at DT_MAX_PROBE.
 *
 * Concurrency model — read-mostly, seqlock-style:
 *   * ONE writer lock in the header (the fc_lock owner-pid idiom from
 *     shmstate.c: dead-owner steal via kill(pid,0)==ESRCH, bounded
 *     wall-clock steal for wedged owners, CAS-from-own-pid unlock).
 *     Writes are rare (a ban insert, a lazy expiry) so a single lock is
 *     plenty.
 *   * readers take NO lock: each slot carries a version word bumped to
 *     odd before mutation and back to even after; a reader snapshots
 *     the version, copies the slot, and retries if the version moved or
 *     was odd.  A bounded retry budget turns a pathological writer into
 *     a reported fault, never a spin — the caller falls open to the
 *     Python chain.
 *   * dt_clear is O(1): it bumps the header epoch, invalidating every
 *     slot at once (slots store the epoch they were written under).
 *     The epoch starts at 1 so freshly zeroed segments parse as stale.
 *
 * Deletion writes key_len = 0 under the slot version bump; probe chains
 * stay valid because readers and the insert scan never early-stop — the
 * whole (bounded) window is scanned, so a freed slot mid-chain cannot
 * hide a live entry behind it.  When a key's window is full of live,
 * unexpired, current-epoch entries the put is REFUSED and a dropped
 * counter is bumped — the entry simply stays Python-only and the chain
 * serves it (fail-open, never evict a live decision).
 *
 * Expiry is the caller's comparison (strictly `now - expires > 0`,
 * matching DynamicDecisionLists lazy expiry to the bit) — the table
 * returns the stored expiry; only dt_put consults `now` so a full
 * window can reuse an already-expired slot.
 */

#include <errno.h>
#include <signal.h>
#include <stdint.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

#define DT_MAGIC 0x626a786474303141LL /* "bjxdt01A" */
#define DT_MAX_PROBE 64
#define DT_KEY_MAX 64
#define DT_READ_RETRIES 1024

typedef struct {
    int64_t magic;
    int64_t capacity;          /* slots; power of two */
    volatile int32_t lock;     /* writer lock: owner pid, 0 = free */
    int32_t _pad0;
    volatile uint64_t epoch;   /* bump = O(1) clear; starts at 1 */
    volatile int64_t count;    /* live entries this epoch (writer-kept) */
    volatile int64_t dropped;  /* refused puts (full window); monotone */
    volatile int64_t sessions; /* mirrored dynamic session-id entries */
    int64_t _pad[9];
} dt_header; /* 128 bytes */

typedef struct {
    volatile uint32_t version; /* seqlock: odd while a write is in flight */
    uint32_t epoch;            /* valid iff == (uint32_t)header->epoch */
    double expires;            /* unix seconds, as stored by Python */
    uint8_t key_len;           /* 0 = free */
    uint8_t decision;
    uint8_t flags;             /* bit0: from_baskerville */
    uint8_t _pad0;
    uint32_t site_hash;        /* FNV-1a of the banning domain (introspection) */
    char key[DT_KEY_MAX];
    int64_t _pad1;
} dt_slot; /* 96 bytes */

static int64_t dt_steal_after_ns = 50 * 1000 * 1000; /* 50 ms default */

void dt_set_steal_ns(int64_t ns) { dt_steal_after_ns = ns; }

static inline int32_t dt_self_tag(void) {
    static int32_t tag; /* benign race: same value from every thread */
    if (tag == 0) {
        tag = (int32_t)getpid();
        if (tag == 0)
            tag = 1;
    }
    return tag;
}

static inline int64_t dt_mono_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

static void dt_lock(dt_header *h) {
    int32_t tag = dt_self_tag();
    int32_t expected = 0;
    if (__atomic_compare_exchange_n(&h->lock, &expected, tag, 0,
                                    __ATOMIC_ACQUIRE, __ATOMIC_RELAXED))
        return;
    int64_t t0 = 0;
    int32_t spins = 0;
    for (;;) {
        int32_t owner = __atomic_load_n(&h->lock, __ATOMIC_RELAXED);
        if (owner == 0) {
            expected = 0;
            if (__atomic_compare_exchange_n(&h->lock, &expected, tag, 0,
                                            __ATOMIC_ACQUIRE,
                                            __ATOMIC_RELAXED))
                return;
            continue;
        }
        if (++spins >= 1024) {
            spins = 0;
            int64_t now = dt_mono_ns();
            if (t0 == 0)
                t0 = now;
            int dead = (owner != tag && kill((pid_t)owner, 0) != 0 &&
                        errno == ESRCH);
            if (dead || now - t0 > dt_steal_after_ns) {
                if (__atomic_compare_exchange_n(&h->lock, &owner, tag, 0,
                                                __ATOMIC_ACQUIRE,
                                                __ATOMIC_RELAXED))
                    return;
            }
        }
    }
}

static inline void dt_unlock(dt_header *h) {
    int32_t tag = dt_self_tag();
    __atomic_compare_exchange_n(&h->lock, &tag, 0, 0, __ATOMIC_RELEASE,
                                __ATOMIC_RELAXED);
}

static inline uint64_t dt_hash(const char *key, int32_t len) {
    uint64_t h = 0xcbf29ce484222325ULL;
    for (int32_t i = 0; i < len; i++) {
        h ^= (uint8_t)key[i];
        h *= 0x100000001b3ULL;
    }
    return h;
}

uint32_t dt_site_hash(const char *key, int32_t len) {
    return (uint32_t)dt_hash(key, len);
}

static inline dt_slot *dt_slots(dt_header *h) {
    return (dt_slot *)((char *)h + sizeof(dt_header));
}

/* seqlock write bracket: the version is odd for the duration */
static inline uint32_t dt_write_begin(dt_slot *s) {
    uint32_t v = __atomic_load_n(&s->version, __ATOMIC_RELAXED);
    __atomic_store_n(&s->version, v + 1, __ATOMIC_RELAXED);
    __atomic_thread_fence(__ATOMIC_RELEASE);
    return v;
}

static inline void dt_write_end(dt_slot *s, uint32_t v) {
    __atomic_thread_fence(__ATOMIC_RELEASE);
    __atomic_store_n(&s->version, v + 2, __ATOMIC_RELEASE);
}

int64_t dt_init(void *base, int64_t capacity) {
    if (capacity < 2 || (capacity & (capacity - 1)) != 0)
        return -1;
    dt_header *h = (dt_header *)base;
    memset(base, 0,
           sizeof(dt_header) + (size_t)capacity * sizeof(dt_slot));
    h->capacity = capacity;
    h->epoch = 1;
    /* magic last, RELEASE: an attacher that sees the magic sees the rest */
    __atomic_store_n(&h->magic, DT_MAGIC, __ATOMIC_RELEASE);
    return (int64_t)(sizeof(dt_header) + (size_t)capacity * sizeof(dt_slot));
}

int64_t dt_check(void *base) {
    dt_header *h = (dt_header *)base;
    if (__atomic_load_n(&h->magic, __ATOMIC_ACQUIRE) != DT_MAGIC)
        return -1;
    return h->capacity;
}

/* Insert or replace.  Returns 0 on success, -1 when the probe window is
 * full of live entries (refused; dropped counter bumped). */
int32_t dt_put(void *base, const char *key, int32_t key_len,
               int32_t decision, int32_t flags, uint32_t site_hash,
               double expires, double now_s) {
    dt_header *h = (dt_header *)base;
    if (key_len <= 0 || key_len > DT_KEY_MAX)
        return -1;
    dt_slot *slots = dt_slots(h);
    uint64_t mask = (uint64_t)h->capacity - 1;
    uint64_t home = dt_hash(key, key_len);
    int64_t window =
        h->capacity < DT_MAX_PROBE ? h->capacity : DT_MAX_PROBE;

    dt_lock(h);
    uint32_t ep = (uint32_t)h->epoch;
    dt_slot *found = 0;
    dt_slot *reuse = 0;
    int reuse_was_live = 0;
    for (int64_t p = 0; p < window; p++) {
        dt_slot *s = &slots[(home + (uint64_t)p) & mask];
        if (s->key_len == 0 || s->epoch != ep) {
            if (!reuse) {
                reuse = s;
                reuse_was_live = 0;
            }
            continue;
        }
        if (s->key_len == (uint8_t)key_len &&
            memcmp(s->key, key, (size_t)key_len) == 0) {
            found = s;
            break;
        }
        if (!reuse && now_s - s->expires > 0.0) {
            reuse = s; /* steal an already-expired live slot */
            reuse_was_live = 1;
        }
    }
    dt_slot *target = found ? found : reuse;
    if (!target) {
        __atomic_fetch_add(&h->dropped, 1, __ATOMIC_RELAXED);
        dt_unlock(h);
        return -1;
    }
    uint32_t v = dt_write_begin(target);
    target->epoch = ep;
    target->expires = expires;
    target->decision = (uint8_t)decision;
    target->flags = (uint8_t)flags;
    target->site_hash = site_hash;
    if (!found) {
        memcpy(target->key, key, (size_t)key_len);
        target->key_len = (uint8_t)key_len;
    }
    dt_write_end(target, v);
    if (!found && !reuse_was_live)
        h->count++;
    dt_unlock(h);
    return 0;
}

/* Lock-free lookup.  Returns 0 on hit (outputs filled), -1 on miss,
 * -2 on a torn-read fault (reader retry budget exhausted — fall open). */
int32_t dt_get(void *base, const char *key, int32_t key_len,
               uint8_t *decision, uint8_t *flags, uint32_t *site_hash,
               double *expires) {
    dt_header *h = (dt_header *)base;
    if (key_len <= 0 || key_len > DT_KEY_MAX)
        return -1;
    dt_slot *slots = dt_slots(h);
    uint64_t mask = (uint64_t)h->capacity - 1;
    uint64_t home = dt_hash(key, key_len);
    uint32_t ep = (uint32_t)__atomic_load_n(&h->epoch, __ATOMIC_ACQUIRE);
    int64_t window =
        h->capacity < DT_MAX_PROBE ? h->capacity : DT_MAX_PROBE;

    for (int64_t p = 0; p < window; p++) {
        dt_slot *s = &slots[(home + (uint64_t)p) & mask];
        uint8_t c_key_len, c_decision, c_flags;
        uint32_t c_site_hash, c_epoch;
        double c_expires;
        char c_key[DT_KEY_MAX];
        int32_t tries = 0;
        for (;;) {
            uint32_t v1 = __atomic_load_n(&s->version, __ATOMIC_ACQUIRE);
            if (!(v1 & 1)) {
                c_key_len = s->key_len;
                c_epoch = s->epoch;
                c_decision = s->decision;
                c_flags = s->flags;
                c_site_hash = s->site_hash;
                c_expires = s->expires;
                if (c_key_len <= DT_KEY_MAX && c_key_len > 0)
                    memcpy(c_key, s->key, c_key_len);
                __atomic_thread_fence(__ATOMIC_ACQUIRE);
                uint32_t v2 =
                    __atomic_load_n(&s->version, __ATOMIC_RELAXED);
                if (v1 == v2)
                    break;
            }
            if (++tries >= DT_READ_RETRIES)
                return -2; /* writer wedged mid-slot: fall open */
        }
        if (c_key_len == 0 || c_epoch != ep)
            continue;
        if (c_key_len == (uint8_t)key_len &&
            memcmp(c_key, key, (size_t)key_len) == 0) {
            *decision = c_decision;
            *flags = c_flags;
            *site_hash = c_site_hash;
            *expires = c_expires;
            return 0;
        }
    }
    return -1;
}

int32_t dt_del(void *base, const char *key, int32_t key_len) {
    dt_header *h = (dt_header *)base;
    if (key_len <= 0 || key_len > DT_KEY_MAX)
        return -1;
    dt_slot *slots = dt_slots(h);
    uint64_t mask = (uint64_t)h->capacity - 1;
    uint64_t home = dt_hash(key, key_len);
    int64_t window =
        h->capacity < DT_MAX_PROBE ? h->capacity : DT_MAX_PROBE;

    dt_lock(h);
    uint32_t ep = (uint32_t)h->epoch;
    for (int64_t p = 0; p < window; p++) {
        dt_slot *s = &slots[(home + (uint64_t)p) & mask];
        if (s->key_len == (uint8_t)key_len && s->epoch == ep &&
            memcmp(s->key, key, (size_t)key_len) == 0) {
            uint32_t v = dt_write_begin(s);
            s->key_len = 0;
            dt_write_end(s, v);
            if (h->count > 0)
                h->count--;
            dt_unlock(h);
            return 0;
        }
    }
    dt_unlock(h);
    return -1;
}

void dt_clear(void *base) {
    dt_header *h = (dt_header *)base;
    dt_lock(h);
    __atomic_fetch_add(&h->epoch, 1, __ATOMIC_RELEASE);
    h->count = 0;
    __atomic_store_n(&h->sessions, 0, __ATOMIC_RELAXED);
    dt_unlock(h);
}

int64_t dt_len(void *base) {
    dt_header *h = (dt_header *)base;
    return __atomic_load_n(&h->count, __ATOMIC_RELAXED);
}

int64_t dt_dropped(void *base) {
    dt_header *h = (dt_header *)base;
    return __atomic_load_n(&h->dropped, __ATOMIC_RELAXED);
}

int64_t dt_session_add(void *base, int64_t delta) {
    dt_header *h = (dt_header *)base;
    int64_t now = __atomic_add_fetch(&h->sessions, delta, __ATOMIC_RELAXED);
    if (now < 0) { /* clamp: a stray double-decrement must not wedge the
                    * session guard permanently negative */
        __atomic_store_n(&h->sessions, 0, __ATOMIC_RELAXED);
        return 0;
    }
    return now;
}

int64_t dt_session_count(void *base) {
    dt_header *h = (dt_header *)base;
    int64_t n = __atomic_load_n(&h->sessions, __ATOMIC_RELAXED);
    return n < 0 ? 0 : n;
}

/* test hook: hold a slot's version odd, as a SIGKILLed writer would */
void dt_test_wedge_slot(void *base, const char *key, int32_t key_len) {
    dt_header *h = (dt_header *)base;
    dt_slot *slots = dt_slots(h);
    uint64_t mask = (uint64_t)h->capacity - 1;
    dt_slot *s = &slots[dt_hash(key, key_len) & mask];
    __atomic_store_n(&s->version, s->version | 1, __ATOMIC_RELEASE);
}

void dt_test_unwedge_slot(void *base, const char *key, int32_t key_len) {
    dt_header *h = (dt_header *)base;
    dt_slot *slots = dt_slots(h);
    uint64_t mask = (uint64_t)h->capacity - 1;
    dt_slot *s = &slots[dt_hash(key, key_len) & mask];
    __atomic_store_n(&s->version, (s->version | 1) + 1, __ATOMIC_RELEASE);
}
