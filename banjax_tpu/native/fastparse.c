/* fastparse.c — native batch parse + byte-class encode for the log tailer
 * hot path.
 *
 * One call scans a newline-joined blob of access-log lines and, per line,
 * performs exactly the splits of banjax_tpu/matcher/encode.py:parse_line
 * (itself the port of the reference's consumeLine splits,
 * /root/reference/internal/regex_rate_limiter.go:126-157):
 *
 *   "<epoch.frac> <ip> <rest>"  with  rest = "<method> <host> <rest2>"
 *
 * plus the staleness check, the ASCII/length host_eval routing, and the
 * byte->class encoding of `rest` for the device NFA — everything between
 * "line arrives" and "device batch" that Python does per line, at memory
 * speed instead of interpreter speed.
 *
 * Exactness contract: timestamps whose text a C strtod round-trip cannot
 * be proven to parse identically to Python float() (underscores, inf/nan
 * spellings, hex floats, out-of-int64 magnitudes) set FLAG_DEFER and the
 * caller re-parses that line with the Python reference path, so observable
 * semantics are bit-identical for every input.
 *
 * Pure C ABI (no Python.h): loaded with ctypes, outputs written into
 * caller-allocated numpy buffers.
 */

#include <math.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#define FLAG_ERROR 1u     /* parse error (reference: error=true) */
#define FLAG_OLD 2u       /* stale line (> cutoff seconds old)   */
#define FLAG_DEFER 4u     /* caller must re-parse with Python    */
#define FLAG_HOST_EVAL 8u /* rest too long / non-ASCII: host re  */

/* Python float() accepts ASCII digits, one '.', exponent, sign; it also
 * accepts "_" digit separators and inf/nan words — those (and anything
 * else unusual) defer to the Python parser. Returns 1 if the span is a
 * plain decimal/exponent float strtod parses identically. */
static int plain_float_span(const uint8_t *s, int64_t n) {
    if (n <= 0 || n > 64)
        return 0;
    int64_t i = 0;
    if (s[i] == '+' || s[i] == '-')
        i++;
    int digits = 0, dot = 0, exp = 0;
    for (; i < n; i++) {
        uint8_t c = s[i];
        if (c >= '0' && c <= '9') {
            digits++;
        } else if (c == '.') {
            if (dot || exp)
                return 0;
            dot = 1;
        } else if (c == 'e' || c == 'E') {
            if (exp || !digits)
                return 0;
            exp = 1;
            if (i + 1 < n && (s[i + 1] == '+' || s[i + 1] == '-'))
                i++;
            if (i + 1 >= n)
                return 0;
        } else {
            return 0;
        }
    }
    return digits > 0;
}

/* Fast path for the overwhelmingly common timestamp shape
 * "digits[.digits]": exact int64 mantissa m and exact power of ten give a
 * single correctly-rounded division, which equals glibc's correctly-
 * rounded strtod — so the result is bit-identical to the slow path (and
 * therefore to Python float()) whenever this returns 1. Anything else
 * (sign, exponent, > 2^53 mantissa, > 18 fraction digits) falls back. */
static int fast_ts(const uint8_t *s, int64_t n, double *out) {
    static const double p10[] = {1,    1e1,  1e2,  1e3,  1e4,  1e5,  1e6,
                                 1e7,  1e8,  1e9,  1e10, 1e11, 1e12, 1e13,
                                 1e14, 1e15, 1e16, 1e17, 1e18};
    int64_t m = 0;
    int fd = 0, seen_dot = 0, digits = 0;
    for (int64_t i = 0; i < n; i++) {
        uint8_t c = s[i];
        if (c >= '0' && c <= '9') {
            if (m >= (int64_t)922337203685477580LL) /* next *10 overflows */
                return 0;
            m = m * 10 + (c - '0');
            digits++;
            if (seen_dot)
                fd++;
        } else if (c == '.' && !seen_dot) {
            seen_dot = 1;
        } else {
            return 0;
        }
    }
    if (!digits || fd > 18)
        return 0;
    if (m > ((int64_t)1 << 53)) /* (double)m no longer exact */
        return 0;
    *out = (double)m / p10[fd];
    return 1;
}

/* One parsed line record; offsets index into the blob. */
typedef struct {
    int64_t ts_ns;
    int64_t ip_off, host_off, rest_off;
    int32_t ip_len, host_len, rest_len;
    uint8_t flags;
} line_rec;

/* Scan blob for newline-separated lines (no trailing newline required).
 * Returns the number of lines found (<= max_lines). */
int64_t fp_split_lines(const uint8_t *blob, int64_t blob_len,
                       int64_t *starts, int64_t *ends, int64_t max_lines) {
    int64_t n = 0, pos = 0;
    while (pos <= blob_len && n < max_lines) {
        const uint8_t *nl = memchr(blob + pos, '\n', (size_t)(blob_len - pos));
        int64_t end = nl ? (int64_t)(nl - blob) : blob_len;
        starts[n] = pos;
        ends[n] = end;
        n++;
        if (!nl)
            break;
        pos = end + 1;
        if (pos == blob_len) /* trailing newline: no empty final line */
            break;
    }
    return n;
}

/* FNV-1a over one span. */
static uint64_t span_hash(const uint8_t *p, int64_t n) {
    uint64_t h = 1469598103934665603ULL;
    for (int64_t i = 0; i < n; i++) {
        h ^= p[i];
        h *= 1099511628211ULL;
    }
    return h;
}

/* Deduplicate (offset, length) spans into first-appearance-ordered ids.
 *
 * Replaces the hot path's numpy window-gather + sort-based unique: at 65k
 * spans the open-addressing probe runs in ~3 ms where the vectorized sort
 * took ~60 ms, and the output ids are already in first-appearance order
 * (the order the per-line reference loop assigns window slots in — a
 * parity surface, see matcher/workset.py).
 *
 * ids_out[n]: 0-based unique id per span. first_out[<=n]: the first span
 * index carrying each id, in id order. table/table_cap: caller-allocated
 * scratch of int64, table_cap a power of two >= 2n, primed to -1 by this
 * function. Returns the unique count. */
int64_t fp_dedup_spans(
    const uint8_t *blob, int64_t blob_len,
    const int64_t *offs, const int32_t *lens, int64_t n,
    int64_t *table, int64_t table_cap,
    int64_t *ids_out, int64_t *first_out) {
    (void)blob_len;
    for (int64_t i = 0; i < table_cap; i++)
        table[i] = -1;
    uint64_t mask = (uint64_t)table_cap - 1;
    int64_t n_uniq = 0;
    for (int64_t i = 0; i < n; i++) {
        const uint8_t *p = blob + offs[i];
        int64_t len = lens[i];
        uint64_t slot = span_hash(p, len) & mask;
        for (;;) {
            int64_t j = table[slot];
            if (j < 0) {
                table[slot] = i;
                ids_out[i] = n_uniq;
                first_out[n_uniq] = i;
                n_uniq++;
                break;
            }
            if (lens[j] == len && memcmp(blob + offs[j], p, (size_t)len) == 0) {
                ids_out[i] = ids_out[j];
                break;
            }
            slot = (slot + 1) & mask;
        }
    }
    return n_uniq;
}

/* Parse + encode every line. Outputs are caller-allocated arrays sized
 * [n_lines] (and cls_out sized [n_lines * max_len], zero-filled by the
 * caller or here). Returns 0. */
int64_t fp_parse_encode(
    const uint8_t *blob, int64_t blob_len,
    const int64_t *starts, const int64_t *ends, int64_t n_lines,
    const int32_t *byte_to_class, /* [256] */
    int32_t max_len,
    double now_unix, double old_cutoff,
    /* outputs */
    int64_t *ts_ns_out, uint8_t *flags_out,
    int64_t *ip_off, int32_t *ip_len,
    int64_t *host_off, int32_t *host_len,
    int64_t *rest_off, int32_t *rest_len,
    int32_t *cls_out, int32_t *lens_out) {
    (void)blob_len;
    for (int64_t li = 0; li < n_lines; li++) {
        line_rec r;
        memset(&r, 0, sizeof(r));
        const uint8_t *line = blob + starts[li];
        int64_t len = ends[li] - starts[li];

        int32_t *cls_row = cls_out + li * (int64_t)max_len;
        memset(cls_row, 0, sizeof(int32_t) * (size_t)max_len);
        lens_out[li] = 0;

        /* split " ", 2 — both splits must yield 3 parts */
        const uint8_t *sp1 = memchr(line, ' ', (size_t)len);
        if (!sp1) {
            r.flags = FLAG_ERROR;
            goto store;
        }
        const uint8_t *p2 = sp1 + 1;
        const uint8_t *sp2 =
            memchr(p2, ' ', (size_t)(len - (p2 - line)));
        if (!sp2) {
            r.flags = FLAG_ERROR;
            goto store;
        }
        /* Python SplitN(" ",3) semantics: "a b " -> ["a","b",""] is 3 parts
         * (empty rest is fine and will fail the inner split) */
        {
            int64_t ts_len = sp1 - line;
            const uint8_t *ip = sp1 + 1;
            int64_t iplen = sp2 - ip;
            const uint8_t *rest = sp2 + 1;
            int64_t restlen = len - (rest - line);

            double ts;
            if (!fast_ts(line, ts_len, &ts)) {
                if (!plain_float_span(line, ts_len)) {
                    r.flags = FLAG_DEFER; /* Python float() may disagree */
                    goto store;
                }
                char tsbuf[80];
                memcpy(tsbuf, line, (size_t)ts_len);
                tsbuf[ts_len] = 0;
                ts = strtod(tsbuf, NULL);
            }
            double scaled = ts * 1e9;
            if (!(scaled > -9.2e18 && scaled < 9.2e18)) {
                r.flags = FLAG_DEFER; /* int64 overflow: Python raises */
                goto store;
            }
            r.ts_ns = (int64_t)scaled; /* C truncation == Python int() */

            r.ip_off = ip - blob;
            r.ip_len = (int32_t)iplen;
            r.rest_off = rest - blob;
            r.rest_len = (int32_t)restlen;

            /* rest split " ", 2 -> method, host, rest2 */
            const uint8_t *rsp1 = memchr(rest, ' ', (size_t)restlen);
            if (!rsp1) {
                r.flags = FLAG_ERROR;
                goto store;
            }
            const uint8_t *hostp = rsp1 + 1;
            const uint8_t *rsp2 =
                memchr(hostp, ' ', (size_t)(restlen - (hostp - rest)));
            if (!rsp2) {
                r.flags = FLAG_ERROR;
                goto store;
            }
            r.host_off = hostp - blob;
            r.host_len = (int32_t)(rsp2 - hostp);

            /* staleness: now - ts_ns/1e9 > cutoff (double math, as Python) */
            if (now_unix - (double)r.ts_ns / 1e9 > old_cutoff) {
                r.flags |= FLAG_OLD;
                goto store;
            }

            /* encode rest: class 0 pad; non-ASCII or over-length -> host */
            if (restlen > (int64_t)max_len) {
                r.flags |= FLAG_HOST_EVAL;
            } else {
                int64_t k;
                for (k = 0; k < restlen; k++) {
                    uint8_t b = rest[k];
                    if (b > 0x7F) {
                        r.flags |= FLAG_HOST_EVAL;
                        memset(cls_row, 0, sizeof(int32_t) * (size_t)k);
                        break;
                    }
                    cls_row[k] = byte_to_class[b];
                }
                if (!(r.flags & FLAG_HOST_EVAL))
                    lens_out[li] = (int32_t)restlen;
            }
        }
    store:
        ts_ns_out[li] = r.ts_ns;
        flags_out[li] = r.flags;
        ip_off[li] = r.ip_off;
        ip_len[li] = r.ip_len;
        host_off[li] = r.host_off;
        host_len[li] = r.host_len;
        rest_off[li] = r.rest_off;
        rest_len[li] = r.rest_len;
    }
    return 0;
}
