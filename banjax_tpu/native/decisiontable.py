"""Loader + wrapper for the shared decision table (decisiontable.c).

The compiled /auth_request fast path's data plane: a shm-resident,
seqlock-read table of already-decided IPs.  The primary process owns the
segment and mirrors every `DynamicDecisionLists` mutation into it
(decisions/dynamic_lists.py `set_mirror`); fastserve workers attach by
name and answer hot lookups with one lock-free probe instead of the
Python decision chain.

Compiled with the same on-demand ctypes pattern as shmstate (native/
shm.py); no compiler => `PyDecisionTable`, an in-process dict with the
same refusal/expiry semantics, keeps single-process deployments on the
fast path.  Every entry point fails open: a closed table, a torn read,
or a refused insert only ever means "serve it through the Python chain".
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import sysconfig
import tempfile
import threading
from multiprocessing import shared_memory
from typing import Optional, Tuple

log = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "decisiontable.c")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

KEY_MAX = 64
SLOT_BYTES = 96
HEADER_BYTES = 128
MAX_PROBE = 64

FLAG_FROM_BASKERVILLE = 0x01


def _so_path() -> str:
    plat = sysconfig.get_platform().replace("-", "_")
    cache_dir = os.environ.get(
        "BANJAX_NATIVE_CACHE", os.path.join(tempfile.gettempdir(), "banjax-native")
    )
    os.makedirs(cache_dir, exist_ok=True)
    src_mtime = int(os.stat(_SRC).st_mtime)
    return os.path.join(cache_dir, f"decisiontable_{plat}_{src_mtime}.so")


def _compile(so: str) -> bool:
    for cc in (os.environ.get("CC"), "cc", "gcc", "clang"):
        if not cc:
            continue
        cmd = [cc, "-O3", "-shared", "-fPIC", "-o", so, _SRC]
        try:
            r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired):
            continue
        if r.returncode == 0:
            return True
        log.debug("decisiontable compile with %s failed: %s", cc, r.stderr[-500:])
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("BANJAX_NO_NATIVE"):
            return None
        so = _so_path()
        if not os.path.exists(so) and not _compile(so):
            log.info("no C compiler; native decision table unavailable")
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError as e:
            log.warning("could not load %s: %s", so, e)
            return None
        vp = ctypes.c_void_p
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        dp = ctypes.POINTER(ctypes.c_double)
        lib.dt_init.restype = ctypes.c_int64
        lib.dt_init.argtypes = [vp, ctypes.c_int64]
        lib.dt_check.restype = ctypes.c_int64
        lib.dt_check.argtypes = [vp]
        lib.dt_put.restype = ctypes.c_int32
        lib.dt_put.argtypes = [
            vp, ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_uint32, ctypes.c_double,
            ctypes.c_double,
        ]
        lib.dt_get.restype = ctypes.c_int32
        lib.dt_get.argtypes = [
            vp, ctypes.c_char_p, ctypes.c_int32, u8p, u8p, u32p, dp,
        ]
        lib.dt_del.restype = ctypes.c_int32
        lib.dt_del.argtypes = [vp, ctypes.c_char_p, ctypes.c_int32]
        lib.dt_clear.restype = None
        lib.dt_clear.argtypes = [vp]
        lib.dt_len.restype = ctypes.c_int64
        lib.dt_len.argtypes = [vp]
        lib.dt_dropped.restype = ctypes.c_int64
        lib.dt_dropped.argtypes = [vp]
        lib.dt_session_add.restype = ctypes.c_int64
        lib.dt_session_add.argtypes = [vp, ctypes.c_int64]
        lib.dt_session_count.restype = ctypes.c_int64
        lib.dt_session_count.argtypes = [vp]
        lib.dt_site_hash.restype = ctypes.c_uint32
        lib.dt_site_hash.argtypes = [ctypes.c_char_p, ctypes.c_int32]
        lib.dt_set_steal_ns.restype = None
        lib.dt_set_steal_ns.argtypes = [ctypes.c_int64]
        lib.dt_test_wedge_slot.restype = None
        lib.dt_test_wedge_slot.argtypes = [vp, ctypes.c_char_p, ctypes.c_int32]
        lib.dt_test_unwedge_slot.restype = None
        lib.dt_test_unwedge_slot.argtypes = [
            vp, ctypes.c_char_p, ctypes.c_int32,
        ]
        _LIB = lib
        return _LIB


def available() -> bool:
    return _load() is not None


def _round_pow2(capacity: int) -> int:
    cap = 2
    while cap < max(2, capacity):
        cap *= 2
    return cap


def _key(ip: str) -> bytes:
    # a zero-length key marks a slot free in the C table; the empty
    # client IP maps to a one-NUL sentinel no real IP collides with
    return ip.encode("utf-8", "surrogatepass")[:KEY_MAX] or b"\x00"


class ShmDecisionTable:
    """The native table over a POSIX shared-memory segment.

    `get(ip)` is the serving hot path: lock-free, one bounded probe, and
    any fault (torn read, closed handle) reads as a miss — the caller
    falls open to the chain.  Mutations take the in-segment writer lock.
    """

    def __init__(self, name: Optional[str] = None, capacity: int = 65536):
        lib = _load()
        if lib is None:
            raise RuntimeError("native decisiontable unavailable (no C compiler?)")
        self._lib = lib
        self._out = threading.local()
        self.capacity = _round_pow2(capacity)
        size = HEADER_BYTES + self.capacity * SLOT_BYTES
        if name is None:
            self._shm = shared_memory.SharedMemory(create=True, size=size)
            self.owner = True
            self._map_base()
            if lib.dt_init(self._base_ptr, self.capacity) < 0:
                raise ValueError(f"capacity {self.capacity} not a power of two")
        else:
            self._shm = shared_memory.SharedMemory(name=name)
            self.owner = False
            # Python ≤3.12: attaching registers the segment with THIS
            # process's resource tracker, which unlinks it when this
            # process exits — yanking the table out from under the
            # primary and the other workers.  Only the creator unlinks.
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister(self._shm._name, "shared_memory")
            except Exception:  # noqa: BLE001 — tracker internals shifted
                pass
            self._map_base()
            cap = lib.dt_check(self._base_ptr)
            if cap < 0:
                raise RuntimeError(f"shm segment {name} is not a dt table")
            self.capacity = int(cap)

    @property
    def name(self) -> str:
        return self._shm.name

    def _map_base(self) -> None:
        tmp = (ctypes.c_char * 1).from_buffer(self._shm.buf)
        self._base_ptr = ctypes.c_void_p(ctypes.addressof(tmp))
        del tmp

    def put(self, ip: str, decision: int, expires: float,
            from_baskerville: bool = False, domain: str = "",
            now: Optional[float] = None) -> bool:
        base = self._base_ptr
        if base is None:
            return False
        key = _key(ip)
        flags = FLAG_FROM_BASKERVILLE if from_baskerville else 0
        dk = domain.encode("utf-8", "surrogatepass")
        site_hash = self._lib.dt_site_hash(dk, len(dk)) if dk else 0
        if now is None:
            import time

            now = time.time()
        return self._lib.dt_put(
            base, key, len(key), int(decision), flags, site_hash,
            float(expires), float(now),
        ) == 0

    def get(self, ip: str) -> Optional[Tuple[int, float, bool]]:
        """(decision, expires, from_baskerville) or None — a torn-read
        fault also reads as None (fail-open, the chain serves it).

        The out-params are preallocated per thread: get() runs once per
        request on the serving hot path, and four ctypes allocations per
        call cost more than the probe itself.
        """
        base = self._base_ptr
        if base is None:
            return None
        key = ip.encode("utf-8", "surrogatepass")
        if len(key) > KEY_MAX or not key:
            key = key[:KEY_MAX] or b"\x00"
        out = self._out
        try:
            cells = out.cells
        except AttributeError:
            cells = out.cells = (
                ctypes.c_uint8(0), ctypes.c_uint8(0),
                ctypes.c_uint32(0), ctypes.c_double(0.0),
            )
            out.refs = tuple(ctypes.byref(c) for c in cells)
        decision, flags, _site_hash, expires = cells
        rc = self._lib.dt_get(base, key, len(key), *out.refs)
        if rc != 0:
            return None
        return (
            int(decision.value),
            float(expires.value),
            bool(flags.value & FLAG_FROM_BASKERVILLE),
        )

    def delete(self, ip: str) -> bool:
        base = self._base_ptr
        if base is None:
            return False
        key = _key(ip)
        return self._lib.dt_del(base, key, len(key)) == 0

    def clear(self) -> None:
        base = self._base_ptr
        if base is not None:
            self._lib.dt_clear(base)

    def __len__(self) -> int:
        base = self._base_ptr
        return int(self._lib.dt_len(base)) if base is not None else 0

    @property
    def dropped(self) -> int:
        base = self._base_ptr
        return int(self._lib.dt_dropped(base)) if base is not None else 0

    def session_add(self, delta: int) -> int:
        base = self._base_ptr
        if base is None:
            return 0
        return int(self._lib.dt_session_add(base, delta))

    def session_count(self) -> int:
        base = self._base_ptr
        return int(self._lib.dt_session_count(base)) if base is not None else 0

    # --- fault-test hooks (tests/unit/test_decisiontable.py) ---

    def set_steal_ns(self, ns: int) -> None:
        self._lib.dt_set_steal_ns(ns)

    def _test_wedge(self, ip: str) -> None:
        key = _key(ip)
        self._lib.dt_test_wedge_slot(self._base_ptr, key, len(key))

    def _test_unwedge(self, ip: str) -> None:
        key = _key(ip)
        self._lib.dt_test_unwedge_slot(self._base_ptr, key, len(key))

    def close(self) -> None:
        self._base_ptr = None
        self._shm.close()

    def unlink(self) -> None:
        if self.owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass


class PyDecisionTable:
    """In-process fallback with identical semantics: bounded capacity,
    refusal (never eviction of a live entry) when full, expired-entry
    reuse, and the same session counter.  Single-process layouts only —
    it cannot be shared across workers."""

    def __init__(self, capacity: int = 65536):
        self.capacity = _round_pow2(capacity)
        self.owner = True
        self._lock = threading.Lock()
        self._entries = {}  # ip -> (decision, expires, from_baskerville)
        self._dropped = 0
        self._sessions = 0
        self._closed = False

    @property
    def name(self) -> None:  # no shm segment to attach to
        return None

    def put(self, ip: str, decision: int, expires: float,
            from_baskerville: bool = False, domain: str = "",
            now: Optional[float] = None) -> bool:
        with self._lock:
            if self._closed:
                return False
            if ip not in self._entries and len(self._entries) >= self.capacity:
                if now is None:
                    import time

                    now = time.time()
                stale = next(
                    (k for k, v in self._entries.items() if now - v[1] > 0),
                    None,
                )
                if stale is None:
                    self._dropped += 1
                    return False
                del self._entries[stale]
            self._entries[ip] = (int(decision), float(expires),
                                 bool(from_baskerville))
            return True

    def get(self, ip: str) -> Optional[Tuple[int, float, bool]]:
        with self._lock:
            # closed reads as a miss, same as the shm table's nulled base
            if self._closed:
                return None
            return self._entries.get(ip)

    def delete(self, ip: str) -> bool:
        with self._lock:
            if self._closed:
                return False
            return self._entries.pop(ip, None) is not None

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._sessions = 0

    def __len__(self) -> int:
        with self._lock:
            return 0 if self._closed else len(self._entries)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def session_add(self, delta: int) -> int:
        with self._lock:
            if self._closed:
                return 0
            self._sessions = max(0, self._sessions + delta)
            return self._sessions

    def session_count(self) -> int:
        with self._lock:
            return 0 if self._closed else self._sessions

    def close(self) -> None:
        self._closed = True

    def unlink(self) -> None:
        pass


def create_decision_table(capacity: int = 65536,
                          name: Optional[str] = None):
    """Factory: the shm table when the native lib is available, else the
    Python fallback (create only — ATTACHING by name requires the native
    lib; returns None so the worker simply serves through the chain)."""
    if available():
        try:
            return ShmDecisionTable(name=name, capacity=capacity)
        except Exception:  # noqa: BLE001 — never block startup on the table
            log.exception("shm decision table unavailable; falling back")
    if name is not None:
        return None
    return PyDecisionTable(capacity=capacity)
