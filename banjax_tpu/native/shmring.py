"""Loader + wrapper for the SPSC shared-memory frame ring (shmring.c).

Co-located fabric shards exchange wire frames through a pair of these
rings (one per direction) instead of loopback TCP — the shm twin of
the warm-tier table in native/shm.py, compiled with the same on-demand
ctypes pattern.  Without a compiler (or under BANJAX_NO_NATIVE) the
pure-Python `PyRing` keeps the layout and semantics with a polling
wait, so the transport negotiation never depends on a toolchain.

A ring moves *bytes*; framing stays wire.py's (4-byte length, 1-byte
type).  Writes are all-or-nothing per frame, so a reader that sees a
header is guaranteed the body is already in the ring — mid-frame
stalls can only come from a wedged/dead peer, and surface as
FrameError exactly like the TCP path.
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct
import subprocess
import sysconfig
import tempfile
import threading
import time
from multiprocessing import shared_memory
from typing import Optional, Tuple

from banjax_tpu.fabric import wire

log = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "shmring.c")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

RING_HEADER = 64
_MAGIC = 0x42414E4A52494E47  # "BANJRING"

# header field offsets (shmring.c ring_hdr)
_OFF_MAGIC = 0
_OFF_SIZE = 8
_OFF_HEAD = 16
_OFF_TAIL = 24

_U64 = struct.Struct("<Q")


def _so_path() -> str:
    plat = sysconfig.get_platform().replace("-", "_")
    cache_dir = os.environ.get(
        "BANJAX_NATIVE_CACHE",
        os.path.join(tempfile.gettempdir(), "banjax-native"),
    )
    os.makedirs(cache_dir, exist_ok=True)
    src_mtime = int(os.stat(_SRC).st_mtime)
    return os.path.join(cache_dir, f"shmring_{plat}_{src_mtime}.so")


def _compile(so: str) -> bool:
    for cc in (os.environ.get("CC"), "cc", "gcc", "clang"):
        if not cc:
            continue
        cmd = [cc, "-O3", "-shared", "-fPIC", "-o", so, _SRC]
        try:
            r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired):
            continue
        if r.returncode == 0:
            return True
        log.debug("shmring compile with %s failed: %s", cc, r.stderr[-500:])
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("BANJAX_NO_NATIVE"):
            return None
        so = _so_path()
        if not os.path.exists(so) and not _compile(so):
            log.info("no C compiler; shm ring falls back to Python polling")
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError as e:
            log.warning("could not load %s: %s", so, e)
            return None
        vp = ctypes.c_void_p
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64 = ctypes.c_int64
        lib.ring_init.restype = i64
        lib.ring_init.argtypes = [vp, i64]
        lib.ring_check.restype = i64
        lib.ring_check.argtypes = [vp]
        lib.ring_readable.restype = i64
        lib.ring_readable.argtypes = [vp]
        lib.ring_write.restype = i64
        lib.ring_write.argtypes = [vp, u8p, i64, i64]
        lib.ring_read.restype = i64
        lib.ring_read.argtypes = [vp, u8p, i64, i64]
        _LIB = lib
    return _LIB


class RingTimeout(OSError):
    """The ring did not make progress within the timeout — wedged or
    dead peer (the writer-side breaker's fast-fail signal)."""


class ShmRing:
    """One direction of a co-located peer link: a single producer and a
    single consumer over one shared-memory segment.  `name=None`
    creates (and later unlinks) the segment; passing a name attaches."""

    def __init__(self, name: Optional[str] = None, capacity: int = 1 << 20):
        if capacity <= 0 or capacity & (capacity - 1):
            raise ValueError(f"ring capacity must be a power of two: {capacity}")
        self._lib = _load()
        size = RING_HEADER + capacity
        if name is None:
            self._shm = shared_memory.SharedMemory(create=True, size=size)
            self.owner = True
            self.capacity = capacity
            self._map_base()
            if self._lib is not None:
                if self._lib.ring_init(self._base_ptr, capacity) != 0:
                    raise ValueError(f"bad ring capacity {capacity}")
            else:
                self._py_init(capacity)
        else:
            self._shm = shared_memory.SharedMemory(name=name)
            self.owner = False
            # attaching must not register the segment with THIS process's
            # resource tracker (it would unlink on exit, yanking the ring
            # out from under the creator) — same dance as native/shm.py
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister(self._shm._name, "shared_memory")
            except Exception:  # noqa: BLE001 — tracker internals shifted
                pass
            self._map_base()
            if self._lib is not None:
                cap = self._lib.ring_check(self._base_ptr)
            else:
                cap = self._py_check()
            if cap < 0:
                raise RuntimeError(f"shm segment {name} is not a fabric ring")
            self.capacity = int(cap)

    @property
    def name(self) -> str:
        return self._shm.name

    def _map_base(self) -> None:
        tmp = (ctypes.c_char * 1).from_buffer(self._shm.buf)
        self._base_ptr = ctypes.c_void_p(ctypes.addressof(tmp))
        del tmp

    # ---- data path (native with Python fallback) ----

    def write(self, data: bytes, timeout_s: float) -> None:
        """All-or-nothing write; RingTimeout if the frame never fits
        (a stalled consumer), FrameError if it can never fit."""
        if len(data) > self.capacity:
            raise wire.FrameError(
                f"frame of {len(data)} bytes exceeds ring capacity "
                f"{self.capacity}"
            )
        if self._lib is not None:
            buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
            rc = self._lib.ring_write(
                self._base_ptr, buf, len(data), int(timeout_s * 1000)
            )
        else:
            rc = self._py_write(data, timeout_s)
        if rc == -1:
            raise RingTimeout(
                f"ring write stalled for {timeout_s:.3f}s "
                f"({self.readable()}/{self.capacity} bytes unread)"
            )
        if rc != 0:
            raise wire.FrameError(f"ring write failed (rc={rc})")

    def read(self, n: int, timeout_s: float) -> Optional[bytes]:
        """Exactly n bytes, or None on timeout (nothing consumed)."""
        if self._lib is not None:
            buf = (ctypes.c_uint8 * n)()
            rc = self._lib.ring_read(
                self._base_ptr, buf, n, int(timeout_s * 1000)
            )
            if rc == -1:
                return None
            if rc != 0:
                raise wire.FrameError(f"ring read failed (rc={rc})")
            return bytes(buf)
        return self._py_read(n, timeout_s)

    def readable(self) -> int:
        if self._lib is not None:
            return int(self._lib.ring_readable(self._base_ptr))
        head = _U64.unpack_from(self._shm.buf, _OFF_HEAD)[0]
        tail = _U64.unpack_from(self._shm.buf, _OFF_TAIL)[0]
        return int(head - tail)

    def occupancy(self) -> float:
        """Fraction of the ring holding unread bytes (the shm-ring
        occupancy gauge)."""
        return min(1.0, self.readable() / float(self.capacity))

    # ---- pure-Python fallback (polling; layout-compatible) ----

    def _py_init(self, capacity: int) -> None:
        buf = self._shm.buf
        buf[:RING_HEADER] = b"\x00" * RING_HEADER
        _U64.pack_into(buf, _OFF_SIZE, capacity)
        _U64.pack_into(buf, _OFF_MAGIC, _MAGIC)

    def _py_check(self) -> int:
        if _U64.unpack_from(self._shm.buf, _OFF_MAGIC)[0] != _MAGIC:
            return -1
        return _U64.unpack_from(self._shm.buf, _OFF_SIZE)[0]

    def _py_write(self, data: bytes, timeout_s: float) -> int:
        buf = self._shm.buf
        n, size = len(data), self.capacity
        deadline = time.monotonic() + timeout_s
        head = _U64.unpack_from(buf, _OFF_HEAD)[0]
        pause = 50e-6
        while True:
            tail = _U64.unpack_from(buf, _OFF_TAIL)[0]
            if size - (head - tail) >= n:
                break
            if time.monotonic() >= deadline:
                return -1
            time.sleep(pause)
            pause = min(pause * 2, 1e-3)
        pos = head & (size - 1)
        first = min(size - pos, n)
        buf[RING_HEADER + pos:RING_HEADER + pos + first] = data[:first]
        if n > first:
            buf[RING_HEADER:RING_HEADER + n - first] = data[first:]
        _U64.pack_into(buf, _OFF_HEAD, head + n)
        return 0

    def _py_read(self, n: int, timeout_s: float) -> Optional[bytes]:
        buf = self._shm.buf
        size = self.capacity
        deadline = time.monotonic() + timeout_s
        tail = _U64.unpack_from(buf, _OFF_TAIL)[0]
        pause = 50e-6
        while True:
            head = _U64.unpack_from(buf, _OFF_HEAD)[0]
            if head - tail >= n:
                break
            if time.monotonic() >= deadline:
                return None
            time.sleep(pause)
            pause = min(pause * 2, 1e-3)
        pos = tail & (size - 1)
        first = min(size - pos, n)
        out = bytes(buf[RING_HEADER + pos:RING_HEADER + pos + first])
        if n > first:
            out += bytes(buf[RING_HEADER:RING_HEADER + n - first])
        _U64.pack_into(buf, _OFF_TAIL, tail + n)
        return out

    # ---- lifecycle ----

    def close(self) -> None:
        try:
            self._base_ptr = None
            self._shm.close()
        except (OSError, BufferError):
            pass
        if self.owner:
            try:
                self._shm.unlink()
            except OSError:
                pass


_FRAME_HEADER = struct.Struct("!IB")


def write_frame(ring: ShmRing, frame: bytes, timeout_s: float) -> None:
    """One whole wire frame, atomically (all-or-nothing)."""
    ring.write(frame, timeout_s)


def read_frame(
    ring: ShmRing, idle_timeout_s: float
) -> Optional[Tuple[int, bytes]]:
    """(ftype, body) or None if no frame started within the idle
    timeout.  Because writes are whole-frame atomic, a visible header
    guarantees the body; a missing body is a corrupt ring."""
    header = ring.read(_FRAME_HEADER.size, idle_timeout_s)
    if header is None:
        return None
    length, ftype = _FRAME_HEADER.unpack(header)
    if length < 1 or length > wire.MAX_FRAME_BYTES:
        raise wire.FrameError(f"bad ring frame length {length}")
    body = ring.read(length - 1, 2.0) if length > 1 else b""
    if body is None:
        raise wire.FrameError(
            f"ring frame torn: header promised {length - 1} body bytes"
        )
    return ftype, body


def available() -> bool:
    """True when the native ring is compiled/loadable (the Python
    fallback still works either way)."""
    return _load() is not None
