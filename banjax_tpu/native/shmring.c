/* shmring.c — SPSC byte ring over POSIX shared memory, futex wakeup.
 *
 * The fabric's co-located-shard transport (fabric/peer.py LinePipe):
 * one producer process writes whole wire frames, one consumer process
 * reads them, no TCP loopback, no syscall per byte.  Layout:
 *
 *   [0]  magic   u64   BANJRING — attach-time type check
 *   [8]  size    u64   data capacity in bytes (power of two)
 *   [16] head    u64   total bytes written  (producer-owned)
 *   [24] tail    u64   total bytes read     (consumer-owned)
 *   [32] wr_seq  u32   bumped after every publish  (consumer waits on it)
 *   [36] rd_seq  u32   bumped after every consume  (producer waits on it)
 *   [40..63]     reserved
 *   [64] data[size]
 *
 * Writes are all-or-nothing: ring_write blocks (futex with a bounded
 * slice, so a missed wake degrades to a poll, never a deadlock) until
 * the whole buffer fits, then copies and publishes with a release
 * store.  ring_read is exact-n-or-timeout.  Single producer, single
 * consumer — no locks anywhere, just acquire/release on head/tail.
 */

#include <errno.h>
#include <linux/futex.h>
#include <stdint.h>
#include <string.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

#define RING_MAGIC 0x42414E4A52494E47ULL /* "BANJRING" */
#define RING_HEADER 64

typedef struct {
    uint64_t magic;
    uint64_t size;
    uint64_t head;
    uint64_t tail;
    uint32_t wr_seq;
    uint32_t rd_seq;
    uint8_t _pad[24];
} ring_hdr;

static int64_t now_ms(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

static void futex_wait_slice(uint32_t *addr, uint32_t val, int64_t slice_ms) {
    struct timespec ts;
    ts.tv_sec = slice_ms / 1000;
    ts.tv_nsec = (slice_ms % 1000) * 1000000;
    syscall(SYS_futex, addr, FUTEX_WAIT, val, &ts, NULL, 0);
}

static void futex_wake_all(uint32_t *addr) {
    syscall(SYS_futex, addr, FUTEX_WAKE, INT32_MAX, NULL, 0);
}

int64_t ring_init(void *base, int64_t capacity) {
    ring_hdr *h = (ring_hdr *)base;
    if (capacity <= 0 || (capacity & (capacity - 1)) != 0)
        return -1;
    memset(h, 0, sizeof(*h));
    h->size = (uint64_t)capacity;
    __atomic_store_n(&h->magic, RING_MAGIC, __ATOMIC_RELEASE);
    return 0;
}

int64_t ring_check(void *base) {
    ring_hdr *h = (ring_hdr *)base;
    if (__atomic_load_n(&h->magic, __ATOMIC_ACQUIRE) != RING_MAGIC)
        return -1;
    return (int64_t)h->size;
}

int64_t ring_readable(void *base) {
    ring_hdr *h = (ring_hdr *)base;
    uint64_t head = __atomic_load_n(&h->head, __ATOMIC_ACQUIRE);
    uint64_t tail = __atomic_load_n(&h->tail, __ATOMIC_ACQUIRE);
    return (int64_t)(head - tail);
}

/* All-or-nothing write of n bytes; 0 on success, -1 on timeout,
 * -2 if n can never fit (n > capacity). */
int64_t ring_write(void *base, const uint8_t *buf, int64_t n,
                   int64_t timeout_ms) {
    ring_hdr *h = (ring_hdr *)base;
    uint8_t *data = (uint8_t *)base + RING_HEADER;
    uint64_t size = h->size;
    if ((uint64_t)n > size)
        return -2;
    int64_t deadline = now_ms() + timeout_ms;
    uint64_t head = h->head; /* producer-owned: plain load is exact */
    for (;;) {
        uint32_t seq = __atomic_load_n(&h->rd_seq, __ATOMIC_ACQUIRE);
        uint64_t tail = __atomic_load_n(&h->tail, __ATOMIC_ACQUIRE);
        if (size - (head - tail) >= (uint64_t)n)
            break;
        int64_t left = deadline - now_ms();
        if (left <= 0)
            return -1;
        futex_wait_slice(&h->rd_seq, seq, left < 10 ? left : 10);
    }
    uint64_t pos = head & (size - 1);
    uint64_t first = size - pos;
    if (first > (uint64_t)n)
        first = (uint64_t)n;
    memcpy(data + pos, buf, first);
    memcpy(data, buf + first, (uint64_t)n - first);
    __atomic_store_n(&h->head, head + (uint64_t)n, __ATOMIC_RELEASE);
    __atomic_add_fetch(&h->wr_seq, 1, __ATOMIC_ACQ_REL);
    futex_wake_all(&h->wr_seq);
    return 0;
}

/* Exact-n read; 0 on success, -1 on timeout (nothing consumed). */
int64_t ring_read(void *base, uint8_t *buf, int64_t n, int64_t timeout_ms) {
    ring_hdr *h = (ring_hdr *)base;
    uint8_t *data = (uint8_t *)base + RING_HEADER;
    uint64_t size = h->size;
    if ((uint64_t)n > size)
        return -2;
    int64_t deadline = now_ms() + timeout_ms;
    uint64_t tail = h->tail; /* consumer-owned: plain load is exact */
    for (;;) {
        uint32_t seq = __atomic_load_n(&h->wr_seq, __ATOMIC_ACQUIRE);
        uint64_t head = __atomic_load_n(&h->head, __ATOMIC_ACQUIRE);
        if (head - tail >= (uint64_t)n)
            break;
        int64_t left = deadline - now_ms();
        if (left <= 0)
            return -1;
        futex_wait_slice(&h->wr_seq, seq, left < 10 ? left : 10);
    }
    uint64_t pos = tail & (size - 1);
    uint64_t first = size - pos;
    if (first > (uint64_t)n)
        first = (uint64_t)n;
    memcpy(buf, data + pos, first);
    if ((uint64_t)n > first)
        memcpy(buf + first, data, (uint64_t)n - first);
    __atomic_store_n(&h->tail, tail + (uint64_t)n, __ATOMIC_RELEASE);
    __atomic_add_fetch(&h->rd_seq, 1, __ATOMIC_ACQ_REL);
    futex_wake_all(&h->rd_seq);
    return 0;
}
