/* slotmgr.c — native slot manager for the device-windows IP table.
 *
 * Replaces the per-distinct-IP Python dict+LRU loop in
 * banjax_tpu/matcher/windows.py (slots_for_unique_ips) with one C call
 * per batch over the unique-IP span array.  PERF round 4 measured that
 * loop at ~15 ms/batch in the all-distinct-IP worst case — the dominant
 * residual on the host path once parse/encode went native.
 *
 * Exact-parity contract with the Python path (the dict loop stays as the
 * differential oracle, tests/unit/test_slotmgr.py):
 *
 *   - two passes per batch, like the Python loop's ordering: pass 1
 *     (sm_lookup_batch) resolves hits and stamps their recency with the
 *     batch sequence number; pass 2 (sm_place_misses) assigns misses in
 *     ip order, popping the free stack first and evicting only at
 *     capacity.
 *   - free-stack order: slots pop ascending (0, 1, 2, ...); grown slots
 *     drain after every pre-grow slot — identical to the Python list's
 *     pop() order across _grow_locked calls.
 *   - eviction victim: minimum (last_used, slot) over assigned, unpinned
 *     slots not touched by THIS batch (last_used < seq) — exactly
 *     np.argmin's first-minimum tie-break.  The sorted candidate list is
 *     built once per batch and re-validated at consumption, which yields
 *     the same victim sequence as the per-miss argmin because nothing
 *     becomes MORE evictable mid-call (pins are frozen, recency only
 *     advances).
 *   - refusal: when every candidate is pinned/touched, return -1 with
 *     earlier misses already placed — the Python loop's partial-state
 *     refusal, after which the caller splits the batch.
 *
 * Recency (last_used, int64 per slot) and pin counts (int32 per slot)
 * stay in caller-owned numpy arrays shared by pointer, so the Python
 * side's vectorized pin release and introspection keep working
 * unchanged.  IP strings are malloc'd copies owned here; the Python
 * wrapper mirrors slot->ip only for misses/evictions (O(changes), not
 * O(ips)).
 *
 * Pure C ABI (no Python.h), loaded with ctypes — same convention as
 * fastparse.c.
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

typedef struct {
    int64_t capacity;
    int64_t assigned;
    /* per-slot ip bytes (malloc'd); NULL = unassigned */
    uint8_t **ip;
    int32_t *ip_len;
    int64_t *tpos; /* slot -> its index in table (for O(1) delete) */
    /* open addressing, linear probe: value = slot, -1 empty, -2 tomb */
    int64_t *table;
    int64_t table_cap; /* power of two, >= 4 * capacity */
    int64_t tombs;
    /* free stack: pop from free_slots[free_top - 1] */
    int32_t *free_slots;
    int64_t free_top;
} sm_t;

static uint64_t sm_hash(const uint8_t *p, int64_t n) {
    uint64_t h = 1469598103934665603ULL;
    for (int64_t i = 0; i < n; i++) {
        h ^= p[i];
        h *= 1099511628211ULL;
    }
    return h;
}

static int64_t pow2_at_least(int64_t n) {
    int64_t c = 64;
    while (c < n)
        c <<= 1;
    return c;
}

/* insertion index for a key known to be ABSENT: first tombstone on the
 * probe path, else the terminating empty cell */
static int64_t sm_insert_pos(const sm_t *sm, const uint8_t *p, int64_t len) {
    uint64_t mask = (uint64_t)sm->table_cap - 1;
    uint64_t s = sm_hash(p, len) & mask;
    int64_t first_tomb = -1;
    for (;;) {
        int64_t v = sm->table[s];
        if (v == -1)
            return first_tomb >= 0 ? first_tomb : (int64_t)s;
        if (v == -2 && first_tomb < 0)
            first_tomb = (int64_t)s;
        s = (s + 1) & mask;
    }
}

static void sm_table_insert(sm_t *sm, int32_t slot) {
    int64_t pos = sm_insert_pos(sm, sm->ip[slot], sm->ip_len[slot]);
    if (sm->table[pos] == -2)
        sm->tombs--;
    sm->table[pos] = slot;
    sm->tpos[slot] = pos;
}

static int sm_table_rebuild(sm_t *sm, int64_t min_cap) {
    int64_t want = pow2_at_least(4 * min_cap);
    if (want != sm->table_cap) {
        int64_t *t = realloc(sm->table, sizeof(int64_t) * (size_t)want);
        if (!t)
            return -1;
        sm->table = t;
        sm->table_cap = want;
    }
    for (int64_t i = 0; i < sm->table_cap; i++)
        sm->table[i] = -1;
    sm->tombs = 0;
    for (int64_t s = 0; s < sm->capacity; s++)
        if (sm->ip[s])
            sm_table_insert(sm, (int32_t)s);
    return 0;
}

void *sm_create(int64_t capacity) {
    if (capacity < 1)
        return NULL;
    sm_t *sm = calloc(1, sizeof(sm_t));
    if (!sm)
        return NULL;
    sm->capacity = capacity;
    sm->ip = calloc((size_t)capacity, sizeof(uint8_t *));
    sm->ip_len = calloc((size_t)capacity, sizeof(int32_t));
    sm->tpos = calloc((size_t)capacity, sizeof(int64_t));
    sm->free_slots = malloc(sizeof(int32_t) * (size_t)capacity);
    sm->table_cap = pow2_at_least(4 * capacity);
    sm->table = malloc(sizeof(int64_t) * (size_t)sm->table_cap);
    if (!sm->ip || !sm->ip_len || !sm->tpos || !sm->free_slots || !sm->table) {
        free(sm->ip);
        free(sm->ip_len);
        free(sm->tpos);
        free(sm->free_slots);
        free(sm->table);
        free(sm);
        return NULL;
    }
    for (int64_t i = 0; i < sm->table_cap; i++)
        sm->table[i] = -1;
    /* pop order 0, 1, 2, ... — list(range(cap-1, -1, -1)).pop() parity */
    for (int64_t i = 0; i < capacity; i++)
        sm->free_slots[i] = (int32_t)(capacity - 1 - i);
    sm->free_top = capacity;
    return sm;
}

void sm_destroy(void *h) {
    sm_t *sm = h;
    if (!sm)
        return;
    for (int64_t s = 0; s < sm->capacity; s++)
        free(sm->ip[s]);
    free(sm->ip);
    free(sm->ip_len);
    free(sm->tpos);
    free(sm->free_slots);
    free(sm->table);
    free(sm);
}

void sm_clear(void *h) {
    sm_t *sm = h;
    for (int64_t s = 0; s < sm->capacity; s++) {
        free(sm->ip[s]);
        sm->ip[s] = NULL;
    }
    sm->assigned = 0;
    sm->tombs = 0;
    for (int64_t i = 0; i < sm->table_cap; i++)
        sm->table[i] = -1;
    for (int64_t i = 0; i < sm->capacity; i++)
        sm->free_slots[i] = (int32_t)(sm->capacity - 1 - i);
    sm->free_top = sm->capacity;
}

int64_t sm_assigned(void *h) { return ((sm_t *)h)->assigned; }

int64_t sm_free_count(void *h) { return ((sm_t *)h)->free_top; }

/* Extend to new_capacity.  New slots land at the BOTTOM of the free
 * stack (popped last, ascending) — matching the Python _grow_locked
 * free-list splice.  Returns 0 ok, -1 on allocation failure (manager
 * left at the old capacity, still consistent). */
int64_t sm_grow(void *h, int64_t new_capacity) {
    sm_t *sm = h;
    int64_t add = new_capacity - sm->capacity;
    if (add <= 0)
        return 0;
    uint8_t **ip = realloc(sm->ip, sizeof(uint8_t *) * (size_t)new_capacity);
    if (!ip)
        return -1;
    sm->ip = ip;
    int32_t *il = realloc(sm->ip_len, sizeof(int32_t) * (size_t)new_capacity);
    if (!il)
        return -1;
    sm->ip_len = il;
    int64_t *tp = realloc(sm->tpos, sizeof(int64_t) * (size_t)new_capacity);
    if (!tp)
        return -1;
    sm->tpos = tp;
    int32_t *fs =
        realloc(sm->free_slots, sizeof(int32_t) * (size_t)new_capacity);
    if (!fs)
        return -1;
    sm->free_slots = fs;
    memset(sm->ip + sm->capacity, 0, sizeof(uint8_t *) * (size_t)add);
    memmove(sm->free_slots + add, sm->free_slots,
            sizeof(int32_t) * (size_t)sm->free_top);
    for (int64_t i = 0; i < add; i++)
        sm->free_slots[i] = (int32_t)(new_capacity - 1 - i);
    sm->free_top += add;
    sm->capacity = new_capacity;
    if (sm->table_cap < 4 * new_capacity)
        /* rebuild OOM keeps the old table — denser but still valid
         * (assigned <= new_capacity <= table_cap / 2 after one double) */
        (void)sm_table_rebuild(sm, new_capacity);
    return 0;
}

/* Pass 1: resolve every ip.  Hits get their slot in slots_out and their
 * recency stamped seq (the Python loop's vectorized hit touch); misses
 * get slots_out = -1 and their index appended to miss_idx_out.  Returns
 * the miss count. */
int64_t sm_lookup_batch(void *h, const uint8_t *blob, const int64_t *offs,
                        const int64_t *lens, int64_t n, int64_t seq,
                        int64_t *last_used, int32_t *slots_out,
                        int64_t *miss_idx_out) {
    sm_t *sm = h;
    uint64_t mask = (uint64_t)sm->table_cap - 1;
    int64_t n_miss = 0;
    for (int64_t i = 0; i < n; i++) {
        const uint8_t *p = blob + offs[i];
        int64_t len = lens[i];
        uint64_t s = sm_hash(p, len) & mask;
        int64_t slot = -1;
        for (;;) {
            int64_t v = sm->table[s];
            if (v == -1)
                break;
            if (v >= 0 && sm->ip_len[v] == (int32_t)len &&
                memcmp(sm->ip[v], p, (size_t)len) == 0) {
                slot = v;
                break;
            }
            s = (s + 1) & mask;
        }
        if (slot >= 0) {
            slots_out[i] = (int32_t)slot;
            last_used[slot] = seq;
        } else {
            slots_out[i] = -1;
            miss_idx_out[n_miss++] = i;
        }
    }
    return n_miss;
}

/* Read-only membership probe over a distinct-ip blob: like pass 1 but
 * WITHOUT the recency stamp — the admission gate must not refresh an
 * IP's LRU position just for asking whether it is resident (a refused
 * batch would otherwise keep every probe victim warm).  Writes 0/1 per
 * ip; returns the number present. */
int64_t sm_contains_batch(void *h, const uint8_t *blob, const int64_t *offs,
                          const int64_t *lens, int64_t n, uint8_t *out) {
    sm_t *sm = h;
    uint64_t mask = (uint64_t)sm->table_cap - 1;
    int64_t found = 0;
    for (int64_t i = 0; i < n; i++) {
        const uint8_t *p = blob + offs[i];
        int64_t len = lens[i];
        uint64_t s = sm_hash(p, len) & mask;
        uint8_t hit = 0;
        for (;;) {
            int64_t v = sm->table[s];
            if (v == -1)
                break;
            if (v >= 0 && sm->ip_len[v] == (int32_t)len &&
                memcmp(sm->ip[v], p, (size_t)len) == 0) {
                hit = 1;
                break;
            }
            s = (s + 1) & mask;
        }
        out[i] = hit;
        found += hit;
    }
    return found;
}

typedef struct {
    int64_t lu;
    int32_t slot;
} sm_cand;

static int cand_cmp(const void *a, const void *b) {
    const sm_cand *x = a, *y = b;
    if (x->lu != y->lu)
        return x->lu < y->lu ? -1 : 1;
    return x->slot < y->slot ? -1 : (x->slot > y->slot ? 1 : 0);
}

/* Pass 2: place every miss, in ip order.  Free slots pop first; at
 * capacity the minimum-(last_used, slot) assigned, unpinned, untouched
 * slot is evicted (evict_out records them in order).  out_counts[0] =
 * evictions performed, out_counts[1] = misses successfully placed.
 * Returns 0, or -1 when an eviction was needed and every candidate is
 * pinned/touched (earlier misses stay placed and MUST be bookkept by
 * the caller — the Python refusal's partial-state semantics). */
int64_t sm_place_misses(void *h, const uint8_t *blob, const int64_t *offs,
                        const int64_t *lens, int64_t seq,
                        const int32_t *pin_counts, int64_t *last_used,
                        int32_t *slots_out, const int64_t *miss_idx,
                        int64_t n_miss, int64_t *evict_out,
                        int64_t *out_counts) {
    sm_t *sm = h;
    sm_cand *cand = NULL;
    int64_t cand_n = 0, cand_i = 0, n_evict = 0, placed = 0;
    int64_t rc = 0;
    for (int64_t m = 0; m < n_miss; m++) {
        int64_t i = miss_idx[m];
        int32_t slot;
        if (sm->free_top > 0) {
            slot = sm->free_slots[--sm->free_top];
        } else {
            if (!cand) {
                cand = malloc(sizeof(sm_cand) * (size_t)sm->capacity);
                if (!cand) {
                    rc = -1;
                    break;
                }
                for (int64_t s2 = 0; s2 < sm->capacity; s2++) {
                    if (sm->ip[s2] && pin_counts[s2] == 0 &&
                        last_used[s2] < seq) {
                        cand[cand_n].lu = last_used[s2];
                        cand[cand_n].slot = (int32_t)s2;
                        cand_n++;
                    }
                }
                qsort(cand, (size_t)cand_n, sizeof(sm_cand), cand_cmp);
            }
            slot = -1;
            while (cand_i < cand_n) {
                sm_cand c = cand[cand_i++];
                /* re-validate: the slot may have been consumed by an
                 * earlier eviction or touched by an earlier placement */
                if (!sm->ip[c.slot] || pin_counts[c.slot] != 0 ||
                    last_used[c.slot] >= seq || last_used[c.slot] != c.lu)
                    continue;
                slot = c.slot;
                break;
            }
            if (slot < 0) {
                rc = -1;
                break;
            }
            free(sm->ip[slot]);
            sm->ip[slot] = NULL;
            sm->table[sm->tpos[slot]] = -2;
            sm->tombs++;
            sm->assigned--;
            evict_out[n_evict++] = slot;
        }
        const uint8_t *p = blob + offs[i];
        int64_t len = lens[i];
        uint8_t *cp = malloc(len > 0 ? (size_t)len : 1);
        if (!cp) {
            /* undo nothing: the slot simply stays free/evicted; report
             * refusal so the caller retries smaller */
            if (sm->free_top < sm->capacity && sm->ip[slot] == NULL)
                sm->free_slots[sm->free_top++] = slot;
            rc = -1;
            break;
        }
        memcpy(cp, p, (size_t)len);
        sm->ip[slot] = cp;
        sm->ip_len[slot] = (int32_t)len;
        if ((sm->assigned + sm->tombs) * 2 > sm->table_cap)
            sm_table_rebuild(sm, sm->capacity);
        sm_table_insert(sm, slot);
        sm->assigned++;
        last_used[slot] = seq;
        slots_out[i] = slot;
        placed++;
    }
    free(cand);
    out_counts[0] = n_evict;
    out_counts[1] = placed;
    return rc;
}
