/* Shared-memory fixed-window rate-limit table.
 *
 * Backs the failed-challenge rate limiter when the HTTP request API runs
 * as multiple SO_REUSEPORT worker processes: every worker maps the same
 * shared-memory segment, so an IP failing challenges round-robined across
 * workers is counted exactly once, like the reference's single-process
 * mutex-guarded map (/root/reference/internal/rate_limit.go:105-156).
 *
 * Layout: one 128-byte header then capacity (power of two) 128-byte slots.
 * Open addressing with linear probing, bounded at FC_MAX_PROBE; no
 * deletion (lookup never early-stops on stolen slots, so probe chains
 * stay valid).  When a key's probe window is full, the stalest expired
 * slot in the window is stolen — semantically identical to keeping it,
 * because an expired window restarts as if first-seen (OUTSIDE_INTERVAL
 * resets hits to 1 exactly like FIRST_TIME does).  If nothing in the
 * window is expired the apply degrades to an unstored first hit and a
 * dropped counter is bumped (visible in metrics).
 *
 * Concurrency: one per-slot spinlock (acquire/release atomics); at most
 * one lock is ever held at a time.  Critical sections are a handful of
 * loads/stores.
 *
 * The lock word stores the OWNER'S PID (0 = free), not a plain flag, so
 * a worker SIGKILLed mid-critical-section (OOM-kill, supervisor
 * escalation) cannot wedge every survivor whose probe chain crosses the
 * slot: a waiter that observes a dead owner (kill(pid, 0) == ESRCH)
 * steals the lock immediately, and any owner — dead or merely wedged —
 * is stolen from after a bounded wall-clock spin (default 50 ms; the
 * critical sections are a few ns, so a live owner held that long is
 * itself a failure).  Unlock is a CAS from our own pid so a robbed
 * owner's late unlock cannot release the thief's lock.  The worst case
 * of a false steal is one corrupted rate-limit slot, never a hang.
 */

#include <errno.h>
#include <signal.h>
#include <stdint.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

#define FC_MAGIC 0x626a7868736d3032LL /* "bjxhsm02" — owner-pid lock words */
#define FC_MAX_PROBE 64
#define FC_KEY_MAX 104

/* match_type values mirror banjax_tpu.decisions.rate_limit.RateLimitMatchType */
#define FC_FIRST_TIME 0
#define FC_OUTSIDE_INTERVAL 1
#define FC_INSIDE_INTERVAL 2
#define FC_EXCEEDED_BIT 0x10
#define FC_DROPPED_BIT 0x100

typedef struct {
    int64_t magic;
    int64_t capacity; /* slots; power of two */
    volatile int64_t dropped;
    int64_t _pad[13];
} fc_header; /* 128 bytes */

typedef struct {
    volatile int32_t lock;
    int32_t key_len; /* 0 = empty */
    int64_t interval_start_ns;
    int32_t num_hits;
    int32_t _pad;
    char key[FC_KEY_MAX];
} fc_slot; /* 128 bytes */

static int64_t fc_steal_after_ns = 50 * 1000 * 1000; /* 50 ms default */

/* test hook: lower the steal bound so the live-owner-steal path is
 * provable without a 50 ms wait per case */
void fc_set_steal_ns(int64_t ns) { fc_steal_after_ns = ns; }

static inline int32_t fc_self_tag(void) {
    /* benign race: every thread of a process writes the same value */
    static int32_t tag;
    if (tag == 0) {
        tag = (int32_t)getpid();
        if (tag == 0)
            tag = 1;
    }
    return tag;
}

static inline int64_t fc_mono_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

static void fc_lock(fc_slot *s) {
    int32_t tag = fc_self_tag();
    int32_t expected = 0;
    if (__atomic_compare_exchange_n(&s->lock, &expected, tag, 0,
                                    __ATOMIC_ACQUIRE, __ATOMIC_RELAXED))
        return; /* uncontended fast path */
    int64_t t0 = 0;
    int32_t spins = 0;
    for (;;) {
        int32_t owner = __atomic_load_n(&s->lock, __ATOMIC_RELAXED);
        if (owner == 0) {
            expected = 0;
            if (__atomic_compare_exchange_n(&s->lock, &expected, tag, 0,
                                            __ATOMIC_ACQUIRE,
                                            __ATOMIC_RELAXED))
                return;
            continue;
        }
        if (++spins >= 1024) { /* syscalls only every ~1k spins */
            spins = 0;
            int64_t now = fc_mono_ns();
            if (t0 == 0)
                t0 = now;
            int dead = (owner != tag && kill((pid_t)owner, 0) != 0 &&
                        errno == ESRCH);
            if (dead || now - t0 > fc_steal_after_ns) {
                if (__atomic_compare_exchange_n(&s->lock, &owner, tag, 0,
                                                __ATOMIC_ACQUIRE,
                                                __ATOMIC_RELAXED))
                    return; /* stolen from a dead/wedged owner */
            }
        }
    }
}

static inline void fc_unlock(fc_slot *s) {
    /* release only if still ours: if the lock was stolen (we were the
     * presumed-dead owner), storing 0 here would unlock the thief */
    int32_t tag = fc_self_tag();
    __atomic_compare_exchange_n(&s->lock, &tag, 0, 0, __ATOMIC_RELEASE,
                                __ATOMIC_RELAXED);
}

static inline uint64_t fc_hash(const char *key, int32_t len) {
    uint64_t h = 0xcbf29ce484222325ULL;
    for (int32_t i = 0; i < len; i++) {
        h ^= (uint8_t)key[i];
        h *= 0x100000001b3ULL;
    }
    return h;
}

static inline fc_slot *fc_slots(void *base) {
    return (fc_slot *)((char *)base + sizeof(fc_header));
}

int64_t fc_init(void *base, int64_t capacity) {
    /* caller provides zeroed shared memory; capacity must be a power of 2 */
    if (capacity <= 0 || (capacity & (capacity - 1)))
        return -1;
    fc_header *h = (fc_header *)base;
    h->capacity = capacity;
    h->dropped = 0;
    __atomic_store_n(&h->magic, FC_MAGIC, __ATOMIC_RELEASE);
    return 0;
}

int64_t fc_check(void *base) {
    fc_header *h = (fc_header *)base;
    if (__atomic_load_n(&h->magic, __ATOMIC_ACQUIRE) != FC_MAGIC)
        return -1;
    return h->capacity;
}

/* The window transition — mirrors FailedChallengeRateLimitStates.apply
 * (rate_limit.go:125-156 quirks: strict >, exceed resets hits to 0). */
static inline int32_t fc_window(fc_slot *s, int64_t now_ns, int32_t threshold,
                                int32_t match, int32_t *out_hits) {
    int32_t rc = match;
    if (match == FC_OUTSIDE_INTERVAL || match == FC_FIRST_TIME) {
        s->num_hits = 1;
        s->interval_start_ns = now_ns;
    } else {
        s->num_hits += 1;
    }
    if (s->num_hits > threshold) {
        s->num_hits = 0;
        rc |= FC_EXCEEDED_BIT;
        *out_hits = 0;
    } else {
        *out_hits = s->num_hits;
    }
    return rc;
}

int32_t fc_apply(void *base, const char *key, int32_t key_len, int64_t now_ns,
                 int64_t interval_ns, int32_t threshold, int32_t *out_hits) {
    fc_header *hdr = (fc_header *)base;
    fc_slot *slots = fc_slots(base);
    uint64_t mask = (uint64_t)hdr->capacity - 1;
    if (key_len > FC_KEY_MAX)
        key_len = FC_KEY_MAX;
    uint64_t home = fc_hash(key, key_len) & mask;

    int64_t stalest_start = INT64_MAX;
    int64_t stalest_idx = -1;
    for (int32_t p = 0; p < FC_MAX_PROBE; p++) {
        fc_slot *s = &slots[(home + p) & mask];
        fc_lock(s);
        if (s->key_len == 0) {
            memcpy(s->key, key, (size_t)key_len);
            s->key_len = key_len;
            int32_t rc = fc_window(s, now_ns, threshold,
                                   FC_FIRST_TIME, out_hits);
            fc_unlock(s);
            return rc;
        }
        if (s->key_len == key_len && memcmp(s->key, key, (size_t)key_len) == 0) {
            int32_t match = (now_ns - s->interval_start_ns > interval_ns)
                                ? FC_OUTSIDE_INTERVAL
                                : FC_INSIDE_INTERVAL;
            int32_t rc = fc_window(s, now_ns, threshold, match,
                                   out_hits);
            fc_unlock(s);
            return rc;
        }
        if (s->interval_start_ns < stalest_start) {
            stalest_start = s->interval_start_ns;
            stalest_idx = (int64_t)((home + p) & mask);
        }
        fc_unlock(s);
    }

    /* probe window full: steal the stalest slot iff its window expired */
    if (stalest_idx >= 0) {
        fc_slot *s = &slots[stalest_idx];
        fc_lock(s);
        if (s->key_len != 0 && now_ns - s->interval_start_ns > interval_ns) {
            memcpy(s->key, key, (size_t)key_len);
            s->key_len = key_len;
            int32_t rc = fc_window(s, now_ns, threshold,
                                   FC_FIRST_TIME, out_hits);
            fc_unlock(s);
            return rc;
        }
        fc_unlock(s);
    }

    /* degraded: transient unstored first hit */
    __atomic_add_fetch(&hdr->dropped, 1, __ATOMIC_RELAXED);
    int32_t rc = FC_FIRST_TIME | FC_DROPPED_BIT;
    if (1 > threshold) {
        rc |= FC_EXCEEDED_BIT;
        *out_hits = 0;
    } else {
        *out_hits = 1;
    }
    return rc;
}

int64_t fc_count(void *base) {
    fc_header *hdr = (fc_header *)base;
    fc_slot *slots = fc_slots(base);
    int64_t n = 0;
    for (int64_t i = 0; i < hdr->capacity; i++)
        if (slots[i].key_len != 0)
            n++;
    return n;
}

int64_t fc_dropped(void *base) {
    fc_header *hdr = (fc_header *)base;
    return __atomic_load_n(&hdr->dropped, __ATOMIC_RELAXED);
}

/* Copy live entries out for format_states / metrics.  Returns the number
 * of entries written (at most max_entries).  keys_blob must hold
 * max_entries*FC_KEY_MAX bytes; entry i's key is keys_blob[i*FC_KEY_MAX :
 * i*FC_KEY_MAX+key_lens[i]]. */
int64_t fc_snapshot(void *base, char *keys_blob, int32_t *key_lens,
                    int32_t *hits, int64_t *starts, int64_t max_entries) {
    fc_header *hdr = (fc_header *)base;
    fc_slot *slots = fc_slots(base);
    int64_t n = 0;
    for (int64_t i = 0; i < hdr->capacity && n < max_entries; i++) {
        fc_slot *s = &slots[i];
        if (s->key_len == 0)
            continue;
        fc_lock(s);
        if (s->key_len != 0) {
            memcpy(keys_blob + n * FC_KEY_MAX, s->key, (size_t)s->key_len);
            key_lens[n] = s->key_len;
            hits[n] = s->num_hits;
            starts[n] = s->interval_start_ns;
            n++;
        }
        fc_unlock(s);
    }
    return n;
}

/* test hooks: plant/read a raw owner tag so the fault suite can simulate
 * a worker killed while holding a slot lock */
void fc_test_lock_slot(void *base, int64_t idx, int32_t tag) {
    __atomic_store_n(&fc_slots(base)[idx].lock, tag, __ATOMIC_RELEASE);
}

int32_t fc_test_slot_owner(void *base, int64_t idx) {
    return __atomic_load_n(&fc_slots(base)[idx].lock, __ATOMIC_ACQUIRE);
}
