/* Shared-memory fixed-window rate-limit table.
 *
 * Backs the failed-challenge rate limiter when the HTTP request API runs
 * as multiple SO_REUSEPORT worker processes: every worker maps the same
 * shared-memory segment, so an IP failing challenges round-robined across
 * workers is counted exactly once, like the reference's single-process
 * mutex-guarded map (/root/reference/internal/rate_limit.go:105-156).
 *
 * Layout: one 128-byte header then capacity (power of two) 128-byte slots.
 * Open addressing with linear probing, bounded at FC_MAX_PROBE; no
 * deletion (lookup never early-stops on stolen slots, so probe chains
 * stay valid).  When a key's probe window is full, the stalest expired
 * slot in the window is stolen — semantically identical to keeping it,
 * because an expired window restarts as if first-seen (OUTSIDE_INTERVAL
 * resets hits to 1 exactly like FIRST_TIME does).  If nothing in the
 * window is expired the apply degrades to an unstored first hit and a
 * dropped counter is bumped (visible in metrics).
 *
 * Concurrency: one per-slot spinlock (acquire/release atomics); at most
 * one lock is ever held at a time.  Critical sections are a handful of
 * loads/stores.
 *
 * The lock word stores the OWNER'S PID (0 = free), not a plain flag, so
 * a worker SIGKILLed mid-critical-section (OOM-kill, supervisor
 * escalation) cannot wedge every survivor whose probe chain crosses the
 * slot: a waiter that observes a dead owner (kill(pid, 0) == ESRCH)
 * steals the lock immediately, and any owner — dead or merely wedged —
 * is stolen from after a bounded wall-clock spin (default 50 ms; the
 * critical sections are a few ns, so a live owner held that long is
 * itself a failure).  Unlock is a CAS from our own pid so a robbed
 * owner's late unlock cannot release the thief's lock.  The worst case
 * of a false steal is one corrupted rate-limit slot, never a hang.
 */

#include <errno.h>
#include <signal.h>
#include <stdint.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

#define FC_MAGIC 0x626a7868736d3032LL /* "bjxhsm02" — owner-pid lock words */
#define FC_MAX_PROBE 64
#define FC_KEY_MAX 104

/* match_type values mirror banjax_tpu.decisions.rate_limit.RateLimitMatchType */
#define FC_FIRST_TIME 0
#define FC_OUTSIDE_INTERVAL 1
#define FC_INSIDE_INTERVAL 2
#define FC_EXCEEDED_BIT 0x10
#define FC_DROPPED_BIT 0x100

typedef struct {
    int64_t magic;
    int64_t capacity; /* slots; power of two */
    volatile int64_t dropped;
    int64_t _pad[13];
} fc_header; /* 128 bytes */

typedef struct {
    volatile int32_t lock;
    int32_t key_len; /* 0 = empty */
    int64_t interval_start_ns;
    int32_t num_hits;
    int32_t _pad;
    char key[FC_KEY_MAX];
} fc_slot; /* 128 bytes */

static int64_t fc_steal_after_ns = 50 * 1000 * 1000; /* 50 ms default */

/* test hook: lower the steal bound so the live-owner-steal path is
 * provable without a 50 ms wait per case */
void fc_set_steal_ns(int64_t ns) { fc_steal_after_ns = ns; }

static inline int32_t fc_self_tag(void) {
    /* benign race: every thread of a process writes the same value */
    static int32_t tag;
    if (tag == 0) {
        tag = (int32_t)getpid();
        if (tag == 0)
            tag = 1;
    }
    return tag;
}

static inline int64_t fc_mono_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

static void fc_lock(fc_slot *s) {
    int32_t tag = fc_self_tag();
    int32_t expected = 0;
    if (__atomic_compare_exchange_n(&s->lock, &expected, tag, 0,
                                    __ATOMIC_ACQUIRE, __ATOMIC_RELAXED))
        return; /* uncontended fast path */
    int64_t t0 = 0;
    int32_t spins = 0;
    for (;;) {
        int32_t owner = __atomic_load_n(&s->lock, __ATOMIC_RELAXED);
        if (owner == 0) {
            expected = 0;
            if (__atomic_compare_exchange_n(&s->lock, &expected, tag, 0,
                                            __ATOMIC_ACQUIRE,
                                            __ATOMIC_RELAXED))
                return;
            continue;
        }
        if (++spins >= 1024) { /* syscalls only every ~1k spins */
            spins = 0;
            int64_t now = fc_mono_ns();
            if (t0 == 0)
                t0 = now;
            int dead = (owner != tag && kill((pid_t)owner, 0) != 0 &&
                        errno == ESRCH);
            if (dead || now - t0 > fc_steal_after_ns) {
                if (__atomic_compare_exchange_n(&s->lock, &owner, tag, 0,
                                                __ATOMIC_ACQUIRE,
                                                __ATOMIC_RELAXED))
                    return; /* stolen from a dead/wedged owner */
            }
        }
    }
}

static inline void fc_unlock(fc_slot *s) {
    /* release only if still ours: if the lock was stolen (we were the
     * presumed-dead owner), storing 0 here would unlock the thief */
    int32_t tag = fc_self_tag();
    __atomic_compare_exchange_n(&s->lock, &tag, 0, 0, __ATOMIC_RELEASE,
                                __ATOMIC_RELAXED);
}

static inline uint64_t fc_hash(const char *key, int32_t len) {
    uint64_t h = 0xcbf29ce484222325ULL;
    for (int32_t i = 0; i < len; i++) {
        h ^= (uint8_t)key[i];
        h *= 0x100000001b3ULL;
    }
    return h;
}

static inline fc_slot *fc_slots(void *base) {
    return (fc_slot *)((char *)base + sizeof(fc_header));
}

int64_t fc_init(void *base, int64_t capacity) {
    /* caller provides zeroed shared memory; capacity must be a power of 2 */
    if (capacity <= 0 || (capacity & (capacity - 1)))
        return -1;
    fc_header *h = (fc_header *)base;
    h->capacity = capacity;
    h->dropped = 0;
    __atomic_store_n(&h->magic, FC_MAGIC, __ATOMIC_RELEASE);
    return 0;
}

int64_t fc_check(void *base) {
    fc_header *h = (fc_header *)base;
    if (__atomic_load_n(&h->magic, __ATOMIC_ACQUIRE) != FC_MAGIC)
        return -1;
    return h->capacity;
}

/* The window transition — mirrors FailedChallengeRateLimitStates.apply
 * (rate_limit.go:125-156 quirks: strict >, exceed resets hits to 0). */
static inline int32_t fc_window(fc_slot *s, int64_t now_ns, int32_t threshold,
                                int32_t match, int32_t *out_hits) {
    int32_t rc = match;
    if (match == FC_OUTSIDE_INTERVAL || match == FC_FIRST_TIME) {
        s->num_hits = 1;
        s->interval_start_ns = now_ns;
    } else {
        s->num_hits += 1;
    }
    if (s->num_hits > threshold) {
        s->num_hits = 0;
        rc |= FC_EXCEEDED_BIT;
        *out_hits = 0;
    } else {
        *out_hits = s->num_hits;
    }
    return rc;
}

int32_t fc_apply(void *base, const char *key, int32_t key_len, int64_t now_ns,
                 int64_t interval_ns, int32_t threshold, int32_t *out_hits) {
    fc_header *hdr = (fc_header *)base;
    fc_slot *slots = fc_slots(base);
    uint64_t mask = (uint64_t)hdr->capacity - 1;
    if (key_len > FC_KEY_MAX)
        key_len = FC_KEY_MAX;
    uint64_t home = fc_hash(key, key_len) & mask;

    int64_t stalest_start = INT64_MAX;
    int64_t stalest_idx = -1;
    for (int32_t p = 0; p < FC_MAX_PROBE; p++) {
        fc_slot *s = &slots[(home + p) & mask];
        fc_lock(s);
        if (s->key_len == 0) {
            memcpy(s->key, key, (size_t)key_len);
            s->key_len = key_len;
            int32_t rc = fc_window(s, now_ns, threshold,
                                   FC_FIRST_TIME, out_hits);
            fc_unlock(s);
            return rc;
        }
        if (s->key_len == key_len && memcmp(s->key, key, (size_t)key_len) == 0) {
            int32_t match = (now_ns - s->interval_start_ns > interval_ns)
                                ? FC_OUTSIDE_INTERVAL
                                : FC_INSIDE_INTERVAL;
            int32_t rc = fc_window(s, now_ns, threshold, match,
                                   out_hits);
            fc_unlock(s);
            return rc;
        }
        if (s->interval_start_ns < stalest_start) {
            stalest_start = s->interval_start_ns;
            stalest_idx = (int64_t)((home + p) & mask);
        }
        fc_unlock(s);
    }

    /* probe window full: steal the stalest slot iff its window expired */
    if (stalest_idx >= 0) {
        fc_slot *s = &slots[stalest_idx];
        fc_lock(s);
        if (s->key_len != 0 && now_ns - s->interval_start_ns > interval_ns) {
            memcpy(s->key, key, (size_t)key_len);
            s->key_len = key_len;
            int32_t rc = fc_window(s, now_ns, threshold,
                                   FC_FIRST_TIME, out_hits);
            fc_unlock(s);
            return rc;
        }
        fc_unlock(s);
    }

    /* degraded: transient unstored first hit */
    __atomic_add_fetch(&hdr->dropped, 1, __ATOMIC_RELAXED);
    int32_t rc = FC_FIRST_TIME | FC_DROPPED_BIT;
    if (1 > threshold) {
        rc |= FC_EXCEEDED_BIT;
        *out_hits = 0;
    } else {
        *out_hits = 1;
    }
    return rc;
}

int64_t fc_count(void *base) {
    fc_header *hdr = (fc_header *)base;
    fc_slot *slots = fc_slots(base);
    int64_t n = 0;
    for (int64_t i = 0; i < hdr->capacity; i++)
        if (slots[i].key_len != 0)
            n++;
    return n;
}

int64_t fc_dropped(void *base) {
    fc_header *hdr = (fc_header *)base;
    return __atomic_load_n(&hdr->dropped, __ATOMIC_RELAXED);
}

/* Copy live entries out for format_states / metrics.  Returns the number
 * of entries written (at most max_entries).  keys_blob must hold
 * max_entries*FC_KEY_MAX bytes; entry i's key is keys_blob[i*FC_KEY_MAX :
 * i*FC_KEY_MAX+key_lens[i]]. */
int64_t fc_snapshot(void *base, char *keys_blob, int32_t *key_lens,
                    int32_t *hits, int64_t *starts, int64_t max_entries) {
    fc_header *hdr = (fc_header *)base;
    fc_slot *slots = fc_slots(base);
    int64_t n = 0;
    for (int64_t i = 0; i < hdr->capacity && n < max_entries; i++) {
        fc_slot *s = &slots[i];
        if (s->key_len == 0)
            continue;
        fc_lock(s);
        if (s->key_len != 0) {
            memcpy(keys_blob + n * FC_KEY_MAX, s->key, (size_t)s->key_len);
            key_lens[n] = s->key_len;
            hits[n] = s->num_hits;
            starts[n] = s->interval_start_ns;
            n++;
        }
        fc_unlock(s);
    }
    return n;
}

/* test hooks: plant/read a raw owner tag so the fault suite can simulate
 * a worker killed while holding a slot lock */
void fc_test_lock_slot(void *base, int64_t idx, int32_t tag) {
    __atomic_store_n(&fc_slots(base)[idx].lock, tag, __ATOMIC_RELEASE);
}

int32_t fc_test_slot_owner(void *base, int64_t idx) {
    return __atomic_load_n(&fc_slots(base)[idx].lock, __ATOMIC_ACQUIRE);
}

/* ------------------------------------------------------------------ *
 * Warm-tier IP window store (mega-state tiering).
 *
 * Holds the full per-rule (num_hits, interval_start) vector of an IP
 * evicted from the device hot tier, so a returning repeat offender
 * refills its window state on slot claim instead of restarting from
 * zero.  One record per IP; the per-rule entries keep their INSERTION
 * order — the hot tier's shadow map is an OrderedDict and a refill
 * round-trip must hand back byte-identical state.
 *
 * Layout: one 128-byte wt_header, then capacity (power of two) records
 * of (128-byte record header + max_rules wt_entry).  Open addressing,
 * linear probe bounded at WT_MAX_PROBE.  Unlike the fc_* table above,
 * take() deletes — key_len -1 marks a tombstone (probes continue past
 * it; key search may still early-stop on a genuine empty because
 * inserts never skip one).
 *
 * Concurrency: NONE here by design.  The only caller is DeviceWindows,
 * which already serializes every slot/shadow mutation under its own
 * lock — the same external-locking convention as slotmgr.c.
 *
 * Full probe window: steal the stalest record iff its last-touch stamp
 * is older than the expiry horizon (an offender's record is refreshed
 * every spill, so live attackers are never the stalest-and-expired
 * victim); otherwise the new put is dropped and counted — bounded
 * memory, never silent.
 */

#define WT_MAGIC 0x626a787774303031LL /* "bjxwt001" */
#define WT_MAX_PROBE 64
#define WT_KEY_MAX 104
#define WT_TOMBSTONE (-1)

typedef struct {
    int64_t magic;
    int64_t capacity;  /* records; power of two */
    int64_t max_rules; /* wt_entry slots per record */
    int64_t count;     /* live records */
    int64_t dropped;   /* puts lost to a full, unexpired probe window */
    int64_t _pad[11];
} wt_header; /* 128 bytes */

typedef struct {
    int32_t key_len; /* 0 = empty, -1 = tombstone */
    int32_t n_entries;
    int64_t stamp_ns; /* last-touch; the steal policy's staleness key */
    char key[WT_KEY_MAX];
    int64_t _pad;
} wt_rec; /* 128 bytes; followed in memory by max_rules wt_entry */

typedef struct {
    int32_t rule_id;
    int32_t hits;
    int64_t start_s;
    int64_t start_ns;
} wt_entry; /* 24 bytes */

static inline int64_t wt_stride(const wt_header *h) {
    return (int64_t)sizeof(wt_rec) + h->max_rules * (int64_t)sizeof(wt_entry);
}

static inline wt_rec *wt_at(void *base, int64_t i) {
    wt_header *h = (wt_header *)base;
    return (wt_rec *)((char *)base + sizeof(wt_header) + i * wt_stride(h));
}

static inline wt_entry *wt_entries(wt_rec *r) {
    return (wt_entry *)((char *)r + sizeof(wt_rec));
}

int64_t wt_init(void *base, int64_t capacity, int64_t max_rules) {
    /* caller provides zeroed memory; capacity must be a power of 2 */
    if (capacity <= 0 || (capacity & (capacity - 1)) || max_rules <= 0)
        return -1;
    wt_header *h = (wt_header *)base;
    h->capacity = capacity;
    h->max_rules = max_rules;
    h->count = 0;
    h->dropped = 0;
    h->magic = WT_MAGIC;
    return 0;
}

int64_t wt_check(void *base) {
    wt_header *h = (wt_header *)base;
    if (h->magic != WT_MAGIC)
        return -1;
    return h->capacity;
}

int64_t wt_len(void *base) { return ((wt_header *)base)->count; }

int64_t wt_dropped(void *base) { return ((wt_header *)base)->dropped; }

void wt_clear(void *base) {
    wt_header *h = (wt_header *)base;
    for (int64_t i = 0; i < h->capacity; i++)
        wt_at(base, i)->key_len = 0;
    h->count = 0;
    h->dropped = 0;
}

static void wt_fill(wt_rec *r, const char *key, int32_t key_len,
                    int64_t now_ns, const int32_t *rule_ids,
                    const int32_t *hits, const int64_t *ss,
                    const int64_t *sns, int64_t n) {
    memcpy(r->key, key, (size_t)key_len);
    r->key_len = key_len;
    r->stamp_ns = now_ns;
    r->n_entries = (int32_t)n;
    wt_entry *e = wt_entries(r);
    for (int64_t k = 0; k < n; k++) {
        e[k].rule_id = rule_ids[k];
        e[k].hits = hits[k];
        e[k].start_s = ss[k];
        e[k].start_ns = sns[k];
    }
}

/* Spill one IP's window vector.  Returns 0 (inserted/updated) or -1
 * (dropped: probe window full of live records younger than expiry). */
int64_t wt_put(void *base, const char *key, int32_t key_len, int64_t now_ns,
               int64_t expiry_ns, const int32_t *rule_ids,
               const int32_t *hits, const int64_t *ss, const int64_t *sns,
               int64_t n) {
    wt_header *h = (wt_header *)base;
    if (key_len > WT_KEY_MAX)
        key_len = WT_KEY_MAX;
    if (n > h->max_rules)
        n = h->max_rules;
    uint64_t mask = (uint64_t)h->capacity - 1;
    uint64_t home = fc_hash(key, key_len) & mask;

    int64_t insert_at = -1;  /* first tombstone-or-empty in the window */
    int64_t stalest_at = -1;
    int64_t stalest_ns = INT64_MAX;
    for (int32_t p = 0; p < WT_MAX_PROBE; p++) {
        int64_t idx = (int64_t)((home + p) & mask);
        wt_rec *r = wt_at(base, idx);
        if (r->key_len == 0) {
            if (insert_at < 0)
                insert_at = idx;
            break; /* a key never lives past a genuine empty */
        }
        if (r->key_len == WT_TOMBSTONE) {
            if (insert_at < 0)
                insert_at = idx;
            continue;
        }
        if (r->key_len == key_len &&
            memcmp(r->key, key, (size_t)key_len) == 0) {
            wt_fill(r, key, key_len, now_ns, rule_ids, hits, ss, sns, n);
            return 0;
        }
        if (r->stamp_ns < stalest_ns) {
            stalest_ns = r->stamp_ns;
            stalest_at = idx;
        }
    }
    if (insert_at >= 0) {
        wt_fill(wt_at(base, insert_at), key, key_len, now_ns, rule_ids,
                hits, ss, sns, n);
        h->count++;
        return 0;
    }
    if (stalest_at >= 0 && now_ns - stalest_ns > expiry_ns) {
        /* steal: the victim's windows all expired, so losing its state
         * is semantically a restart-as-first-seen, like fc_apply */
        wt_fill(wt_at(base, stalest_at), key, key_len, now_ns, rule_ids,
                hits, ss, sns, n);
        h->dropped++;
        return 0;
    }
    h->dropped++;
    return -1;
}

/* Move semantics for refill: copy the record's entries out and delete
 * it.  Returns the entry count, or -1 when the key is absent. */
int64_t wt_take(void *base, const char *key, int32_t key_len,
                int32_t *rule_ids_out, int32_t *hits_out, int64_t *ss_out,
                int64_t *sns_out) {
    wt_header *h = (wt_header *)base;
    if (key_len > WT_KEY_MAX)
        key_len = WT_KEY_MAX;
    uint64_t mask = (uint64_t)h->capacity - 1;
    uint64_t home = fc_hash(key, key_len) & mask;
    for (int32_t p = 0; p < WT_MAX_PROBE; p++) {
        wt_rec *r = wt_at(base, (int64_t)((home + p) & mask));
        if (r->key_len == 0)
            return -1;
        if (r->key_len == WT_TOMBSTONE)
            continue;
        if (r->key_len == key_len &&
            memcmp(r->key, key, (size_t)key_len) == 0) {
            int64_t n = r->n_entries;
            wt_entry *e = wt_entries(r);
            for (int64_t k = 0; k < n; k++) {
                rule_ids_out[k] = e[k].rule_id;
                hits_out[k] = e[k].hits;
                ss_out[k] = e[k].start_s;
                sns_out[k] = e[k].start_ns;
            }
            r->key_len = WT_TOMBSTONE;
            h->count--;
            return n;
        }
    }
    return -1;
}

/* Non-deleting read (introspection: DeviceWindows.get / format_states
 * must see warm-spilled state).  Same contract as wt_take otherwise. */
int64_t wt_get(void *base, const char *key, int32_t key_len,
               int32_t *rule_ids_out, int32_t *hits_out, int64_t *ss_out,
               int64_t *sns_out) {
    wt_header *h = (wt_header *)base;
    if (key_len > WT_KEY_MAX)
        key_len = WT_KEY_MAX;
    uint64_t mask = (uint64_t)h->capacity - 1;
    uint64_t home = fc_hash(key, key_len) & mask;
    for (int32_t p = 0; p < WT_MAX_PROBE; p++) {
        wt_rec *r = wt_at(base, (int64_t)((home + p) & mask));
        if (r->key_len == 0)
            return -1;
        if (r->key_len == WT_TOMBSTONE)
            continue;
        if (r->key_len == key_len &&
            memcmp(r->key, key, (size_t)key_len) == 0) {
            int64_t n = r->n_entries;
            wt_entry *e = wt_entries(r);
            for (int64_t k = 0; k < n; k++) {
                rule_ids_out[k] = e[k].rule_id;
                hits_out[k] = e[k].hits;
                ss_out[k] = e[k].start_s;
                sns_out[k] = e[k].start_ns;
            }
            return n;
        }
    }
    return -1;
}

/* Copy live keys out (table order) for introspection.  keys_blob must
 * hold max_entries*WT_KEY_MAX bytes.  Returns the number written. */
int64_t wt_snapshot_keys(void *base, char *keys_blob, int32_t *key_lens,
                         int64_t max_entries) {
    wt_header *h = (wt_header *)base;
    int64_t n = 0;
    for (int64_t i = 0; i < h->capacity && n < max_entries; i++) {
        wt_rec *r = wt_at(base, i);
        if (r->key_len <= 0)
            continue;
        memcpy(keys_blob + n * WT_KEY_MAX, r->key, (size_t)r->key_len);
        key_lens[n] = r->key_len;
        n++;
    }
    return n;
}

/* Batched membership probe over a distinct-ip blob (the admission
 * check's fast path: one C call per batch, not one per IP).  Writes
 * 0/1 per ip into out; returns the number present. */
int64_t wt_contains_batch(void *base, const uint8_t *blob,
                          const int64_t *offs, const int64_t *lens,
                          int64_t n, uint8_t *out) {
    wt_header *h = (wt_header *)base;
    uint64_t mask = (uint64_t)h->capacity - 1;
    int64_t found = 0;
    for (int64_t i = 0; i < n; i++) {
        const char *key = (const char *)blob + offs[i];
        int32_t key_len = (int32_t)lens[i];
        if (key_len > WT_KEY_MAX)
            key_len = WT_KEY_MAX;
        uint64_t home = fc_hash(key, key_len) & mask;
        uint8_t hit = 0;
        for (int32_t p = 0; p < WT_MAX_PROBE; p++) {
            wt_rec *r = wt_at(base, (int64_t)((home + p) & mask));
            if (r->key_len == 0)
                break;
            if (r->key_len == WT_TOMBSTONE)
                continue;
            if (r->key_len == key_len &&
                memcmp(r->key, key, (size_t)key_len) == 0) {
                hit = 1;
                break;
            }
        }
        out[i] = hit;
        found += hit;
    }
    return found;
}
