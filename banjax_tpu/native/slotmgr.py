"""ctypes shim for the native slot manager (native/slotmgr.c).

`create(capacity)` returns a SlotManager, or None when no C compiler is
available — callers (matcher/windows.py) keep the Python dict+LRU path,
which doubles as the differential oracle (tests/unit/test_slotmgr.py).

Same compile-on-first-use convention as banjax_tpu/native/__init__.py
(cached .so keyed by platform + source mtime; BANJAX_NO_NATIVE disables).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import sysconfig
import tempfile
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

log = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "slotmgr.c")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_u8p = ctypes.POINTER(ctypes.c_uint8)
_i32p = ctypes.POINTER(ctypes.c_int32)
_i64p = ctypes.POINTER(ctypes.c_int64)


def _P(a: np.ndarray, t):
    return a.ctypes.data_as(t)


def _so_path() -> str:
    plat = sysconfig.get_platform().replace("-", "_")
    cache_dir = os.environ.get(
        "BANJAX_NATIVE_CACHE",
        os.path.join(tempfile.gettempdir(), "banjax-native"),
    )
    os.makedirs(cache_dir, exist_ok=True)
    src_mtime = int(os.stat(_SRC).st_mtime)
    return os.path.join(cache_dir, f"slotmgr_{plat}_{src_mtime}.so")


def _compile(so: str) -> bool:
    for cc in (os.environ.get("CC"), "cc", "gcc", "clang"):
        if not cc:
            continue
        cmd = [cc, "-O3", "-shared", "-fPIC", "-o", so, _SRC]
        try:
            r = subprocess.run(
                cmd, capture_output=True, text=True, timeout=120
            )
        except (OSError, subprocess.TimeoutExpired):
            continue
        if r.returncode == 0:
            return True
        log.debug("slotmgr compile with %s failed: %s", cc, r.stderr[-500:])
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("BANJAX_NO_NATIVE"):
            return None
        so = _so_path()
        if not os.path.exists(so) and not _compile(so):
            log.info("no C compiler available; Python slot-manager path")
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError as e:
            log.warning("could not load %s: %s", so, e)
            return None
        lib.sm_create.restype = ctypes.c_void_p
        lib.sm_create.argtypes = [ctypes.c_int64]
        lib.sm_destroy.restype = None
        lib.sm_destroy.argtypes = [ctypes.c_void_p]
        lib.sm_clear.restype = None
        lib.sm_clear.argtypes = [ctypes.c_void_p]
        lib.sm_assigned.restype = ctypes.c_int64
        lib.sm_assigned.argtypes = [ctypes.c_void_p]
        lib.sm_free_count.restype = ctypes.c_int64
        lib.sm_free_count.argtypes = [ctypes.c_void_p]
        lib.sm_grow.restype = ctypes.c_int64
        lib.sm_grow.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.sm_lookup_batch.restype = ctypes.c_int64
        lib.sm_lookup_batch.argtypes = [
            ctypes.c_void_p, _u8p, _i64p, _i64p, ctypes.c_int64,
            ctypes.c_int64, _i64p, _i32p, _i64p,
        ]
        lib.sm_place_misses.restype = ctypes.c_int64
        lib.sm_place_misses.argtypes = [
            ctypes.c_void_p, _u8p, _i64p, _i64p, ctypes.c_int64,
            _i32p, _i64p, _i32p, _i64p, ctypes.c_int64, _i64p, _i64p,
        ]
        lib.sm_contains_batch.restype = ctypes.c_int64
        lib.sm_contains_batch.argtypes = [
            ctypes.c_void_p, _u8p, _i64p, _i64p, ctypes.c_int64, _u8p,
        ]
        _LIB = lib
        log.info("native slotmgr loaded (%s)", so)
        return _LIB


def _encode_ips(ips: Sequence[str]) -> Tuple[bytes, np.ndarray, np.ndarray]:
    """One blob + (offset, length) spans for a distinct-ip list.  The
    common all-ASCII case is one join + one encode; byte lengths equal
    char lengths so the per-ip work is a C-speed map(len)."""
    n = len(ips)
    joined = "".join(ips)
    blob = joined.encode("utf-8", "surrogatepass")
    if len(blob) == len(joined):
        lens = np.fromiter(map(len, ips), dtype=np.int64, count=n)
    else:  # non-ASCII ip strings (oracle inputs, not real traffic)
        lens = np.fromiter(
            (len(ip.encode("utf-8", "surrogatepass")) for ip in ips),
            dtype=np.int64, count=n,
        )
    offs = np.zeros(n, dtype=np.int64)
    if n > 1:
        np.cumsum(lens[:-1], out=offs[1:])
    return blob, offs, lens


class SlotManager:
    """One native ip->slot table.  All calls must be externally locked —
    DeviceWindows holds its own lock around every use, exactly as it does
    for the Python dict path."""

    def __init__(self, lib: ctypes.CDLL, handle: int, capacity: int):
        self._lib = lib
        self._h = handle
        self.capacity = capacity

    def close(self) -> None:
        if self._h:
            self._lib.sm_destroy(self._h)
            self._h = None

    def __del__(self):  # noqa: D105
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    def clear(self) -> None:
        self._lib.sm_clear(self._h)

    def assigned(self) -> int:
        return int(self._lib.sm_assigned(self._h))

    def free_count(self) -> int:
        return int(self._lib.sm_free_count(self._h))

    def grow(self, new_capacity: int) -> None:
        if self._lib.sm_grow(self._h, new_capacity) != 0:
            raise MemoryError("slotmgr grow failed")
        self.capacity = new_capacity

    def lookup_batch(
        self, ips: Sequence[str], batch_seq: int, last_used: np.ndarray
    ):
        """Pass 1 over a DISTINCT ip list: resolve hits (stamping their
        recency with batch_seq) and collect misses.  Returns (slots
        int32 [n] with -1 per miss, miss_idx int64 [m], ctx) — pass ctx
        straight to place_misses.  The caller may grow the manager (and
        its device arrays) between the two passes; the passes re-take
        the array pointers, so reallocation in between is safe."""
        n = len(ips)
        slots = np.empty(n, dtype=np.int32)
        if n == 0:
            return slots, np.empty(0, np.int64), None
        blob, offs, lens = _encode_ips(ips)
        buf = np.frombuffer(blob, dtype=np.uint8) if blob else np.zeros(
            1, dtype=np.uint8
        )
        miss_idx = np.empty(n, dtype=np.int64)
        n_miss = int(self._lib.sm_lookup_batch(
            self._h, _P(buf, _u8p), _P(offs, _i64p), _P(lens, _i64p), n,
            batch_seq, _P(last_used, _i64p), _P(slots, _i32p),
            _P(miss_idx, _i64p),
        ))
        return slots, miss_idx[:n_miss], (buf, offs, lens)

    def place_misses(
        self,
        ctx,
        slots: np.ndarray,
        miss_idx: np.ndarray,
        batch_seq: int,
        pin_counts: np.ndarray,
        last_used: np.ndarray,
    ):
        """Pass 2: place every miss, in ip order (free stack first, then
        minimum-(last_used, slot) eviction).  Returns (placed_miss_idx,
        evict_slots, ok).  ok=False is the refusal (every eviction
        candidate pinned): placements made BEFORE the refusal persist,
        and placed_miss_idx/evict_slots report exactly those — the
        caller must bookkeep them (slot->ip mirror, pending device
        evictions) before splitting the batch, as in the Python path."""
        n_miss = len(miss_idx)
        if n_miss == 0:
            return np.empty(0, np.int64), np.empty(0, np.int64), True
        buf, offs, lens = ctx
        evict = np.empty(n_miss, dtype=np.int64)
        counts = np.zeros(2, dtype=np.int64)
        rc = int(self._lib.sm_place_misses(
            self._h, _P(buf, _u8p), _P(offs, _i64p), _P(lens, _i64p),
            batch_seq, _P(pin_counts, _i32p), _P(last_used, _i64p),
            _P(slots, _i32p), _P(miss_idx, _i64p), n_miss,
            _P(evict, _i64p), _P(counts, _i64p),
        ))
        return miss_idx[: int(counts[1])], evict[: int(counts[0])], rc == 0

    def contains_batch(self, ips: Sequence[str]) -> np.ndarray:
        """bool [n] membership over a DISTINCT ip list, with NO recency
        stamp — the slot-admission gate's hot-tier check (a refused
        batch must not refresh its probe victims' LRU position)."""
        n = len(ips)
        out = np.zeros(n, dtype=np.uint8)
        if n == 0:
            return out.astype(bool)
        blob, offs, lens = _encode_ips(ips)
        buf = np.frombuffer(blob, dtype=np.uint8) if blob else np.zeros(
            1, dtype=np.uint8
        )
        self._lib.sm_contains_batch(
            self._h, _P(buf, _u8p), _P(offs, _i64p), _P(lens, _i64p), n,
            _P(out, _u8p),
        )
        return out.astype(bool)


def create(capacity: int) -> Optional[SlotManager]:
    """A SlotManager, or None when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    h = lib.sm_create(capacity)
    if not h:
        return None
    return SlotManager(lib, h, capacity)
