"""Loader + wrapper for the shared-memory rate-limit table (shmstate.c).

`ShmFailedChallengeStates` is a drop-in for
`banjax_tpu.decisions.rate_limit.FailedChallengeRateLimitStates` whose
state lives in a POSIX shared-memory segment, so N SO_REUSEPORT worker
processes count an IP's failed challenges exactly once — the
multi-process twin of the reference's mutex-guarded map
(/root/reference/internal/rate_limit.go:105-156).

Compiled with the same on-demand ctypes pattern as fastparse (see
native/__init__.py); unavailable compiler => callers keep the
single-process Python path.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import sysconfig
import tempfile
import threading
import time
from multiprocessing import shared_memory
from typing import List, Optional, Tuple

import numpy as np

from banjax_tpu.decisions.rate_limit import RateLimitMatchType, RateLimitResult

log = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "shmstate.c")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

KEY_MAX = 104
SLOT_BYTES = 128
HEADER_BYTES = 128

MATCH_MASK = 0x0F
EXCEEDED_BIT = 0x10
DROPPED_BIT = 0x100


def _so_path() -> str:
    plat = sysconfig.get_platform().replace("-", "_")
    cache_dir = os.environ.get(
        "BANJAX_NATIVE_CACHE", os.path.join(tempfile.gettempdir(), "banjax-native")
    )
    os.makedirs(cache_dir, exist_ok=True)
    src_mtime = int(os.stat(_SRC).st_mtime)
    return os.path.join(cache_dir, f"shmstate_{plat}_{src_mtime}.so")


def _compile(so: str) -> bool:
    for cc in (os.environ.get("CC"), "cc", "gcc", "clang"):
        if not cc:
            continue
        cmd = [cc, "-O3", "-shared", "-fPIC", "-o", so, _SRC]
        try:
            r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired):
            continue
        if r.returncode == 0:
            return True
        log.debug("shmstate compile with %s failed: %s", cc, r.stderr[-500:])
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("BANJAX_NO_NATIVE"):
            return None
        so = _so_path()
        if not os.path.exists(so) and not _compile(so):
            log.info("no C compiler; shared-memory rate-limit state unavailable")
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError as e:
            log.warning("could not load %s: %s", so, e)
            return None
        vp = ctypes.c_void_p
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.fc_init.restype = ctypes.c_int64
        lib.fc_init.argtypes = [vp, ctypes.c_int64]
        lib.fc_check.restype = ctypes.c_int64
        lib.fc_check.argtypes = [vp]
        lib.fc_apply.restype = ctypes.c_int32
        lib.fc_apply.argtypes = [
            vp, ctypes.c_char_p, ctypes.c_int32, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int32, i32p,
        ]
        lib.fc_count.restype = ctypes.c_int64
        lib.fc_count.argtypes = [vp]
        lib.fc_dropped.restype = ctypes.c_int64
        lib.fc_dropped.argtypes = [vp]
        lib.fc_snapshot.restype = ctypes.c_int64
        lib.fc_snapshot.argtypes = [
            vp, ctypes.c_char_p, i32p, i32p, i64p, ctypes.c_int64,
        ]
        lib.fc_set_steal_ns.restype = None
        lib.fc_set_steal_ns.argtypes = [ctypes.c_int64]
        lib.fc_test_lock_slot.restype = None
        lib.fc_test_lock_slot.argtypes = [vp, ctypes.c_int64, ctypes.c_int32]
        lib.fc_test_slot_owner.restype = ctypes.c_int32
        lib.fc_test_slot_owner.argtypes = [vp, ctypes.c_int64]
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.wt_init.restype = ctypes.c_int64
        lib.wt_init.argtypes = [vp, ctypes.c_int64, ctypes.c_int64]
        lib.wt_check.restype = ctypes.c_int64
        lib.wt_check.argtypes = [vp]
        lib.wt_len.restype = ctypes.c_int64
        lib.wt_len.argtypes = [vp]
        lib.wt_dropped.restype = ctypes.c_int64
        lib.wt_dropped.argtypes = [vp]
        lib.wt_clear.restype = None
        lib.wt_clear.argtypes = [vp]
        lib.wt_put.restype = ctypes.c_int64
        lib.wt_put.argtypes = [
            vp, ctypes.c_char_p, ctypes.c_int32, ctypes.c_int64,
            ctypes.c_int64, i32p, i32p, i64p, i64p, ctypes.c_int64,
        ]
        lib.wt_take.restype = ctypes.c_int64
        lib.wt_take.argtypes = [
            vp, ctypes.c_char_p, ctypes.c_int32, i32p, i32p, i64p, i64p,
        ]
        lib.wt_get.restype = ctypes.c_int64
        lib.wt_get.argtypes = [
            vp, ctypes.c_char_p, ctypes.c_int32, i32p, i32p, i64p, i64p,
        ]
        lib.wt_snapshot_keys.restype = ctypes.c_int64
        lib.wt_snapshot_keys.argtypes = [
            vp, ctypes.c_char_p, i32p, ctypes.c_int64,
        ]
        lib.wt_contains_batch.restype = ctypes.c_int64
        lib.wt_contains_batch.argtypes = [
            vp, u8p, i64p, i64p, ctypes.c_int64, u8p,
        ]
        _LIB = lib
        return _LIB


def available() -> bool:
    return _load() is not None


class ShmFailedChallengeStates:
    """Failed-challenge rate limiter over a shared-memory table.

    Same `apply(ip, config) -> RateLimitResult` / `__len__` /
    `format_states()` interface as the Python class; iteration order of
    format_states is table order (hash order), not insertion order — the
    route's output contract does not pin an order.
    """

    def __init__(self, name: Optional[str] = None, capacity: int = 65536):
        lib = _load()
        if lib is None:
            raise RuntimeError("native shmstate unavailable (no C compiler?)")
        self._lib = lib
        self.capacity = capacity
        size = HEADER_BYTES + capacity * SLOT_BYTES
        if name is None:
            self._shm = shared_memory.SharedMemory(create=True, size=size)
            self.owner = True
            self._map_base()
            if lib.fc_init(self._base_ptr, capacity) != 0:
                raise ValueError(f"capacity {capacity} not a power of two")
        else:
            self._shm = shared_memory.SharedMemory(name=name)
            self.owner = False
            # Python ≤3.12: attaching registers the segment with THIS
            # process's resource tracker, which unlinks it when this
            # process exits — yanking the table out from under the primary
            # and the other workers.  Only the creator may unlink.
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister(self._shm._name, "shared_memory")
            except Exception:  # noqa: BLE001 — tracker internals shifted
                pass
            self._map_base()
            cap = lib.fc_check(self._base_ptr)
            if cap < 0:
                raise RuntimeError(f"shm segment {name} is not an fc table")
            self.capacity = int(cap)

    @property
    def name(self) -> str:
        return self._shm.name

    def _map_base(self) -> None:
        # extract the raw mapping address once; the transient from_buffer
        # export is dropped immediately so close() can release the mmap.
        # The address stays valid while self._shm is open (object lifetime).
        tmp = (ctypes.c_char * 1).from_buffer(self._shm.buf)
        self._base_ptr = ctypes.c_void_p(ctypes.addressof(tmp))
        del tmp

    def _base(self) -> ctypes.c_void_p:
        return self._base_ptr

    def apply(self, ip: str, config) -> RateLimitResult:
        # a zero-length key would mark the slot "empty" in the C table, so
        # an empty client IP maps to a one-NUL sentinel (no real IP
        # collides with it); the Python limiter counts "" normally and so
        # must we
        key = ip.encode("utf-8", "surrogatepass")[:KEY_MAX] or b"\x00"
        interval_ns = (
            config.too_many_failed_challenges_interval_seconds * 1_000_000_000
        )
        threshold = config.too_many_failed_challenges_threshold
        base = self._base()
        if base is None:  # closed (shutdown); NULL would segfault in C
            return RateLimitResult()
        hits = ctypes.c_int32(0)
        rc = self._lib.fc_apply(
            base, key, len(key), time.time_ns(), interval_ns,
            threshold, ctypes.byref(hits),
        )
        return RateLimitResult(
            match_type=RateLimitMatchType(rc & MATCH_MASK),
            exceeded=bool(rc & EXCEEDED_BIT),
        )

    def __len__(self) -> int:
        base = self._base()
        return int(self._lib.fc_count(base)) if base is not None else 0

    @property
    def dropped(self) -> int:
        base = self._base()
        return int(self._lib.fc_dropped(base)) if base is not None else 0

    def _entries(self) -> List[Tuple[str, int, int]]:
        if self._base() is None:
            return []
        cap = self.capacity
        blob = ctypes.create_string_buffer(cap * KEY_MAX)
        key_lens = np.zeros(cap, dtype=np.int32)
        hits = np.zeros(cap, dtype=np.int32)
        starts = np.zeros(cap, dtype=np.int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        n = self._lib.fc_snapshot(
            self._base(), blob, key_lens.ctypes.data_as(i32p),
            hits.ctypes.data_as(i32p), starts.ctypes.data_as(i64p), cap,
        )
        out = []
        for i in range(int(n)):
            raw = blob.raw[i * KEY_MAX : i * KEY_MAX + int(key_lens[i])]
            if raw == b"\x00":
                raw = b""  # the empty-ip sentinel (see apply)
            out.append(
                (raw.decode("utf-8", "surrogatepass"), int(hits[i]), int(starts[i]))
            )
        return out

    def format_states(self) -> str:
        # same line format as FailedChallengeRateLimitStates.format_states
        return "".join(
            f"{ip},: interval_start: {start}, num hits: {hits}\n"
            for ip, hits, start in self._entries()
        )

    # --- fault-test hooks (tests/faults/test_shm_lock_steal.py) ---

    def set_steal_ns(self, ns: int) -> None:
        """Lower the lock-steal bound (process-wide, test-only)."""
        self._lib.fc_set_steal_ns(ns)

    def _test_lock_slot(self, idx: int, tag: int) -> None:
        """Plant a raw owner tag on slot idx, simulating a holder that
        died (dead pid tag) or wedged (live pid tag) mid-critical-section."""
        self._lib.fc_test_lock_slot(self._base(), idx, tag)

    def _test_slot_owner(self, idx: int) -> int:
        return int(self._lib.fc_test_slot_owner(self._base(), idx))

    def close(self) -> None:
        self._base_ptr = None
        self._shm.close()

    def unlink(self) -> None:
        if self.owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass


# ---------------------------------------------------------------------------
# Warm-tier IP window store (mega-state tiering).
#
# The middle tier of the three-tier hierarchy: device slots (hot) spill a
# victim's full per-rule (num_hits, interval_start) vector here on
# eviction, and a returning IP refills byte-identically on slot claim.
# Entry order inside a record is preserved (the hot tier's shadow is an
# OrderedDict; insertion order is part of the round-trip contract).
#
# Two implementations with one interface:
#   ShmWarmTier — the C table appended to shmstate.c (wt_*), backed by a
#       shared-memory segment; O(1) probe-bounded put/take and a single
#       batched membership call per admission check.
#   PyWarmTier  — bounded-OrderedDict fallback when no C compiler is
#       available; same steal-iff-expired / drop-and-count overflow
#       policy, approximated globally instead of per probe window (it
#       drops strictly less often, never more).
#
# Both are externally locked by DeviceWindows, like slotmgr.


# (rule_id, num_hits, interval_start_s, interval_start_ns) — exactly the
# shadow map's value tuple with the rule id made explicit
WarmEntries = List[Tuple[int, int, int, int]]

WT_KEY_MAX = 104
WT_REC_HEADER_BYTES = 128
WT_ENTRY_BYTES = 24


def _wt_key(ip: str) -> bytes:
    # same empty-key sentinel as the fc table: key_len 0 means "empty
    # slot" in C, so the empty ip maps to one NUL byte
    return ip.encode("utf-8", "surrogatepass")[:WT_KEY_MAX] or b"\x00"


class ShmWarmTier:
    """Warm-tier table over a shared-memory segment (wt_* in shmstate.c).

    All calls must be externally locked — DeviceWindows holds its own
    lock around every use, the slotmgr convention.
    """

    def __init__(
        self,
        capacity: int = 1 << 20,
        max_rules: int = 16,
        expiry_ns: int = 300 * 1_000_000_000,
        name: Optional[str] = None,
    ):
        lib = _load()
        if lib is None:
            raise RuntimeError("native shmstate unavailable (no C compiler?)")
        self._lib = lib
        cap = 1
        while cap < max(2, capacity):
            cap *= 2
        self.capacity = cap
        self.max_rules = max(1, int(max_rules))
        self.expiry_ns = int(expiry_ns)
        stride = WT_REC_HEADER_BYTES + self.max_rules * WT_ENTRY_BYTES
        size = HEADER_BYTES + cap * stride
        if name is None:
            self._shm = shared_memory.SharedMemory(create=True, size=size)
            self.owner = True
            self._map_base()
            if lib.wt_init(self._base_ptr, cap, self.max_rules) != 0:
                raise ValueError(f"bad warm-tier geometry {cap}x{max_rules}")
        else:
            self._shm = shared_memory.SharedMemory(name=name)
            self.owner = False
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister(self._shm._name, "shared_memory")
            except Exception:  # noqa: BLE001 — tracker internals shifted
                pass
            self._map_base()
            if lib.wt_check(self._base_ptr) < 0:
                raise RuntimeError(f"shm segment {name} is not a wt table")
        # scratch output arrays reused by take/get (max_rules is small)
        self._rid = np.zeros(self.max_rules, dtype=np.int32)
        self._hits = np.zeros(self.max_rules, dtype=np.int32)
        self._ss = np.zeros(self.max_rules, dtype=np.int64)
        self._sns = np.zeros(self.max_rules, dtype=np.int64)

    @property
    def name(self) -> str:
        return self._shm.name

    _map_base = ShmFailedChallengeStates._map_base

    def put(self, ip: str, entries: WarmEntries, now_ns: int) -> bool:
        """Spill one IP's window vector; False when the put was dropped
        (probe window full of live, unexpired records)."""
        base = self._base_ptr
        if base is None or not entries:
            return False
        key = _wt_key(ip)
        n = min(len(entries), self.max_rules)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        rid = np.fromiter((e[0] for e in entries), np.int32, count=len(entries))
        hits = np.fromiter((e[1] for e in entries), np.int32, count=len(entries))
        ss = np.fromiter((e[2] for e in entries), np.int64, count=len(entries))
        sns = np.fromiter((e[3] for e in entries), np.int64, count=len(entries))
        rc = self._lib.wt_put(
            base, key, len(key), now_ns, self.expiry_ns,
            rid.ctypes.data_as(i32p), hits.ctypes.data_as(i32p),
            ss.ctypes.data_as(i64p), sns.ctypes.data_as(i64p), n,
        )
        return rc == 0

    def _read(self, ip: str, fn) -> Optional[WarmEntries]:
        base = self._base_ptr
        if base is None:
            return None
        key = _wt_key(ip)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        n = int(fn(
            base, key, len(key),
            self._rid.ctypes.data_as(i32p), self._hits.ctypes.data_as(i32p),
            self._ss.ctypes.data_as(i64p), self._sns.ctypes.data_as(i64p),
        ))
        if n < 0:
            return None
        return [
            (int(self._rid[k]), int(self._hits[k]),
             int(self._ss[k]), int(self._sns[k]))
            for k in range(n)
        ]

    def take(self, ip: str) -> Optional[WarmEntries]:
        """Refill read: the record is deleted (move semantics — the state
        now lives in the hot tier's shadow again)."""
        return self._read(ip, self._lib.wt_take)

    def peek(self, ip: str) -> Optional[WarmEntries]:
        """Non-deleting read for introspection (get/format_states)."""
        return self._read(ip, self._lib.wt_get)

    def contains_batch(self, ips) -> np.ndarray:
        """bool [n] membership over a distinct-ip list — one C call."""
        n = len(ips)
        out = np.zeros(n, dtype=np.uint8)
        base = self._base_ptr
        if n == 0 or base is None:
            return out.astype(bool)
        from banjax_tpu.native.slotmgr import _encode_ips

        blob, offs, lens = _encode_ips([ip if ip else "\x00" for ip in ips])
        buf = np.frombuffer(blob, dtype=np.uint8)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        self._lib.wt_contains_batch(
            base, buf.ctypes.data_as(u8p), offs.ctypes.data_as(i64p),
            lens.ctypes.data_as(i64p), n, out.ctypes.data_as(u8p),
        )
        return out.astype(bool)

    def __contains__(self, ip: str) -> bool:
        return bool(self.contains_batch([ip])[0])

    def keys(self) -> List[str]:
        base = self._base_ptr
        if base is None:
            return []
        cap = self.capacity
        blob = ctypes.create_string_buffer(cap * WT_KEY_MAX)
        key_lens = np.zeros(cap, dtype=np.int32)
        i32p = ctypes.POINTER(ctypes.c_int32)
        n = int(self._lib.wt_snapshot_keys(
            base, blob, key_lens.ctypes.data_as(i32p), cap
        ))
        out = []
        for i in range(n):
            raw = blob.raw[i * WT_KEY_MAX : i * WT_KEY_MAX + int(key_lens[i])]
            if raw == b"\x00":
                raw = b""
            out.append(raw.decode("utf-8", "surrogatepass"))
        return out

    def __len__(self) -> int:
        base = self._base_ptr
        return int(self._lib.wt_len(base)) if base is not None else 0

    @property
    def dropped(self) -> int:
        base = self._base_ptr
        return int(self._lib.wt_dropped(base)) if base is not None else 0

    def clear(self) -> None:
        base = self._base_ptr
        if base is not None:
            self._lib.wt_clear(base)

    def close(self) -> None:
        self._base_ptr = None
        self._shm.close()

    def unlink(self) -> None:
        if self.owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass


class PyWarmTier:
    """Pure-Python warm tier (no C compiler): bounded OrderedDict with
    the same steal-iff-expired overflow policy, evaluated globally — the
    stalest record overall is the steal candidate, so this path drops at
    most as often as the probe-window-bounded C table."""

    def __init__(
        self,
        capacity: int = 1 << 20,
        max_rules: int = 16,
        expiry_ns: int = 300 * 1_000_000_000,
    ):
        cap = 1
        while cap < max(2, capacity):
            cap *= 2
        self.capacity = cap
        self.max_rules = max(1, int(max_rules))
        self.expiry_ns = int(expiry_ns)
        self._dropped = 0
        # ip -> (stamp_ns, entries); order = last-touch (stalest first)
        from collections import OrderedDict

        self._d: "OrderedDict[str, Tuple[int, WarmEntries]]" = OrderedDict()

    def put(self, ip: str, entries: WarmEntries, now_ns: int) -> bool:
        if not entries:
            return False
        entries = entries[: self.max_rules]
        if ip in self._d:
            self._d[ip] = (now_ns, entries)
            self._d.move_to_end(ip)
            return True
        if len(self._d) >= self.capacity:
            stale_ip, (stamp, _) = next(iter(self._d.items()))
            if now_ns - stamp > self.expiry_ns:
                del self._d[stale_ip]
                self._dropped += 1
            else:
                self._dropped += 1
                return False
        self._d[ip] = (now_ns, entries)
        return True

    def take(self, ip: str) -> Optional[WarmEntries]:
        v = self._d.pop(ip, None)
        return None if v is None else v[1]

    def peek(self, ip: str) -> Optional[WarmEntries]:
        v = self._d.get(ip)
        return None if v is None else v[1]

    def contains_batch(self, ips) -> np.ndarray:
        d = self._d
        return np.fromiter((ip in d for ip in ips), bool, count=len(ips))

    def __contains__(self, ip: str) -> bool:
        return ip in self._d

    def keys(self) -> List[str]:
        return list(self._d)

    def __len__(self) -> int:
        return len(self._d)

    @property
    def dropped(self) -> int:
        return self._dropped

    def clear(self) -> None:
        self._d.clear()
        self._dropped = 0

    def close(self) -> None:
        self._d.clear()

    def unlink(self) -> None:
        pass


def create_warm_tier(
    capacity: int = 1 << 20,
    max_rules: int = 16,
    expiry_ns: int = 300 * 1_000_000_000,
):
    """A warm tier: the shm-backed C table when the native library is
    available, else the Python fallback — same interface either way."""
    if available():
        try:
            return ShmWarmTier(
                capacity=capacity, max_rules=max_rules, expiry_ns=expiry_ns
            )
        except Exception:  # noqa: BLE001 — shm creation can fail (rlimits)
            log.exception("shm warm tier unavailable; Python fallback")
    return PyWarmTier(
        capacity=capacity, max_rules=max_rules, expiry_ns=expiry_ns
    )
