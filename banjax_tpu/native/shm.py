"""Loader + wrapper for the shared-memory rate-limit table (shmstate.c).

`ShmFailedChallengeStates` is a drop-in for
`banjax_tpu.decisions.rate_limit.FailedChallengeRateLimitStates` whose
state lives in a POSIX shared-memory segment, so N SO_REUSEPORT worker
processes count an IP's failed challenges exactly once — the
multi-process twin of the reference's mutex-guarded map
(/root/reference/internal/rate_limit.go:105-156).

Compiled with the same on-demand ctypes pattern as fastparse (see
native/__init__.py); unavailable compiler => callers keep the
single-process Python path.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import sysconfig
import tempfile
import threading
import time
from multiprocessing import shared_memory
from typing import List, Optional, Tuple

import numpy as np

from banjax_tpu.decisions.rate_limit import RateLimitMatchType, RateLimitResult

log = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "shmstate.c")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

KEY_MAX = 104
SLOT_BYTES = 128
HEADER_BYTES = 128

MATCH_MASK = 0x0F
EXCEEDED_BIT = 0x10
DROPPED_BIT = 0x100


def _so_path() -> str:
    plat = sysconfig.get_platform().replace("-", "_")
    cache_dir = os.environ.get(
        "BANJAX_NATIVE_CACHE", os.path.join(tempfile.gettempdir(), "banjax-native")
    )
    os.makedirs(cache_dir, exist_ok=True)
    src_mtime = int(os.stat(_SRC).st_mtime)
    return os.path.join(cache_dir, f"shmstate_{plat}_{src_mtime}.so")


def _compile(so: str) -> bool:
    for cc in (os.environ.get("CC"), "cc", "gcc", "clang"):
        if not cc:
            continue
        cmd = [cc, "-O3", "-shared", "-fPIC", "-o", so, _SRC]
        try:
            r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired):
            continue
        if r.returncode == 0:
            return True
        log.debug("shmstate compile with %s failed: %s", cc, r.stderr[-500:])
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("BANJAX_NO_NATIVE"):
            return None
        so = _so_path()
        if not os.path.exists(so) and not _compile(so):
            log.info("no C compiler; shared-memory rate-limit state unavailable")
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError as e:
            log.warning("could not load %s: %s", so, e)
            return None
        vp = ctypes.c_void_p
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.fc_init.restype = ctypes.c_int64
        lib.fc_init.argtypes = [vp, ctypes.c_int64]
        lib.fc_check.restype = ctypes.c_int64
        lib.fc_check.argtypes = [vp]
        lib.fc_apply.restype = ctypes.c_int32
        lib.fc_apply.argtypes = [
            vp, ctypes.c_char_p, ctypes.c_int32, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int32, i32p,
        ]
        lib.fc_count.restype = ctypes.c_int64
        lib.fc_count.argtypes = [vp]
        lib.fc_dropped.restype = ctypes.c_int64
        lib.fc_dropped.argtypes = [vp]
        lib.fc_snapshot.restype = ctypes.c_int64
        lib.fc_snapshot.argtypes = [
            vp, ctypes.c_char_p, i32p, i32p, i64p, ctypes.c_int64,
        ]
        lib.fc_set_steal_ns.restype = None
        lib.fc_set_steal_ns.argtypes = [ctypes.c_int64]
        lib.fc_test_lock_slot.restype = None
        lib.fc_test_lock_slot.argtypes = [vp, ctypes.c_int64, ctypes.c_int32]
        lib.fc_test_slot_owner.restype = ctypes.c_int32
        lib.fc_test_slot_owner.argtypes = [vp, ctypes.c_int64]
        _LIB = lib
        return _LIB


def available() -> bool:
    return _load() is not None


class ShmFailedChallengeStates:
    """Failed-challenge rate limiter over a shared-memory table.

    Same `apply(ip, config) -> RateLimitResult` / `__len__` /
    `format_states()` interface as the Python class; iteration order of
    format_states is table order (hash order), not insertion order — the
    route's output contract does not pin an order.
    """

    def __init__(self, name: Optional[str] = None, capacity: int = 65536):
        lib = _load()
        if lib is None:
            raise RuntimeError("native shmstate unavailable (no C compiler?)")
        self._lib = lib
        self.capacity = capacity
        size = HEADER_BYTES + capacity * SLOT_BYTES
        if name is None:
            self._shm = shared_memory.SharedMemory(create=True, size=size)
            self.owner = True
            self._map_base()
            if lib.fc_init(self._base_ptr, capacity) != 0:
                raise ValueError(f"capacity {capacity} not a power of two")
        else:
            self._shm = shared_memory.SharedMemory(name=name)
            self.owner = False
            # Python ≤3.12: attaching registers the segment with THIS
            # process's resource tracker, which unlinks it when this
            # process exits — yanking the table out from under the primary
            # and the other workers.  Only the creator may unlink.
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister(self._shm._name, "shared_memory")
            except Exception:  # noqa: BLE001 — tracker internals shifted
                pass
            self._map_base()
            cap = lib.fc_check(self._base_ptr)
            if cap < 0:
                raise RuntimeError(f"shm segment {name} is not an fc table")
            self.capacity = int(cap)

    @property
    def name(self) -> str:
        return self._shm.name

    def _map_base(self) -> None:
        # extract the raw mapping address once; the transient from_buffer
        # export is dropped immediately so close() can release the mmap.
        # The address stays valid while self._shm is open (object lifetime).
        tmp = (ctypes.c_char * 1).from_buffer(self._shm.buf)
        self._base_ptr = ctypes.c_void_p(ctypes.addressof(tmp))
        del tmp

    def _base(self) -> ctypes.c_void_p:
        return self._base_ptr

    def apply(self, ip: str, config) -> RateLimitResult:
        # a zero-length key would mark the slot "empty" in the C table, so
        # an empty client IP maps to a one-NUL sentinel (no real IP
        # collides with it); the Python limiter counts "" normally and so
        # must we
        key = ip.encode("utf-8", "surrogatepass")[:KEY_MAX] or b"\x00"
        interval_ns = (
            config.too_many_failed_challenges_interval_seconds * 1_000_000_000
        )
        threshold = config.too_many_failed_challenges_threshold
        base = self._base()
        if base is None:  # closed (shutdown); NULL would segfault in C
            return RateLimitResult()
        hits = ctypes.c_int32(0)
        rc = self._lib.fc_apply(
            base, key, len(key), time.time_ns(), interval_ns,
            threshold, ctypes.byref(hits),
        )
        return RateLimitResult(
            match_type=RateLimitMatchType(rc & MATCH_MASK),
            exceeded=bool(rc & EXCEEDED_BIT),
        )

    def __len__(self) -> int:
        base = self._base()
        return int(self._lib.fc_count(base)) if base is not None else 0

    @property
    def dropped(self) -> int:
        base = self._base()
        return int(self._lib.fc_dropped(base)) if base is not None else 0

    def _entries(self) -> List[Tuple[str, int, int]]:
        if self._base() is None:
            return []
        cap = self.capacity
        blob = ctypes.create_string_buffer(cap * KEY_MAX)
        key_lens = np.zeros(cap, dtype=np.int32)
        hits = np.zeros(cap, dtype=np.int32)
        starts = np.zeros(cap, dtype=np.int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        n = self._lib.fc_snapshot(
            self._base(), blob, key_lens.ctypes.data_as(i32p),
            hits.ctypes.data_as(i32p), starts.ctypes.data_as(i64p), cap,
        )
        out = []
        for i in range(int(n)):
            raw = blob.raw[i * KEY_MAX : i * KEY_MAX + int(key_lens[i])]
            if raw == b"\x00":
                raw = b""  # the empty-ip sentinel (see apply)
            out.append(
                (raw.decode("utf-8", "surrogatepass"), int(hits[i]), int(starts[i]))
            )
        return out

    def format_states(self) -> str:
        # same line format as FailedChallengeRateLimitStates.format_states
        return "".join(
            f"{ip},: interval_start: {start}, num hits: {hits}\n"
            for ip, hits, start in self._entries()
        )

    # --- fault-test hooks (tests/faults/test_shm_lock_steal.py) ---

    def set_steal_ns(self, ns: int) -> None:
        """Lower the lock-steal bound (process-wide, test-only)."""
        self._lib.fc_set_steal_ns(ns)

    def _test_lock_slot(self, idx: int, tag: int) -> None:
        """Plant a raw owner tag on slot idx, simulating a holder that
        died (dead pid tag) or wedged (live pid tag) mid-critical-section."""
        self._lib.fc_test_lock_slot(self._base(), idx, tag)

    def _test_slot_owner(self, idx: int) -> int:
        return int(self._lib.fc_test_slot_owner(self._base(), idx))

    def close(self) -> None:
        self._base_ptr = None
        self._shm.close()

    def unlink(self) -> None:
        if self.owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass
