"""Device-mesh sharding for the batched NFA matcher.

The reference scales horizontally by running N independent banjax+nginx
edges with no shared state (SURVEY.md §2.3); the TPU-native equivalent is a
`jax.sharding.Mesh` over two axes:

  * `dp` — data parallel over the line batch: each device classifies a
    shard of the encoded lines (the "log shards across cores" strategy of
    BASELINE.json's "one pmap'd pass").
  * `rp` — rule parallel over the packed NFA word axis: each device holds a
    slice of the transition masks (the VMEM budget constraint of SURVEY.md
    §7.3 hard part 3). rulec lays branches out so none straddles an `rp`
    shard boundary, so the in-shard packed shift never needs a cross-device
    carry; the only collective is one `psum` of accept bits over `rp`,
    riding ICI.

The per-device body is the SAME Pallas kernel the single-chip product path
runs (matcher/kernels/nfa_match.py) — each rp member scans its own word
slab with a one-shard grid; `backend="xla"` swaps in the nfa_jax scan and
`backend="pallas-interpret"` runs the kernel as plain JAX (the CPU-mesh CI
and dryrun path). `ShardedMatchBackend` is the batch-level wrapper
TpuMatcher plugs into `_match_bits` when a mesh is configured.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from banjax_tpu.obs import trace

try:
    from jax import shard_map as _shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(f, mesh, in_specs, out_specs, check_vma=False):
    """Version shim: the replication-check kwarg was renamed check_rep →
    check_vma across jax releases; accept either installed spelling."""
    try:
        return _shard_map(f, mesh, in_specs=in_specs, out_specs=out_specs,
                          check_vma=check_vma)
    except TypeError:
        return _shard_map(f, mesh, in_specs=in_specs, out_specs=out_specs,
                          check_rep=check_vma)

from banjax_tpu.matcher import nfa_jax
from banjax_tpu.matcher.kernels import nfa_match as pallas_nfa
from banjax_tpu.matcher.rulec import CompiledRules


def make_mesh(n_devices: int, rp: int = 1) -> Mesh:
    """Mesh of shape (dp = n_devices // rp, rp)."""
    if n_devices % rp != 0:
        raise ValueError(f"n_devices {n_devices} not divisible by rp {rp}")
    devices = np.array(jax.devices()[:n_devices]).reshape(n_devices // rp, rp)
    return Mesh(devices, axis_names=("dp", "rp"))


def _param_specs() -> Dict[str, P]:
    return {
        "b_table": P(None, "rp"),
        "shift_in": P("rp"),
        "inject_always": P("rp"),
        "inject_start": P("rp"),
        "selfloop": P("rp"),
        "accept_any": P("rp"),
        "accept_end": P("rp"),
        # branch/extraction arrays are replicated; each rp member selects its
        # own branches by word-index range
        "acc_word": P(),
        "acc_mask": P(),
        "branch_rule": P(),
        "always_match": P(),
        "empty_only": P(),
    }


def _extract_local(
    acc,                 # [b, W_local] uint32 — this shard's accept words
    lens_local,          # [b] int32
    acc_word, acc_mask, branch_rule, always_match, empty_only,
    n_rules: int,
    words_per_shard: int,
):
    """Shard-local accept extraction + the rp psum combine (shared by the
    XLA and Pallas bodies — the only collective in the device step)."""
    shard = jax.lax.axis_index("rp")
    local_w = acc_word - shard * words_per_shard
    in_shard = (local_w >= 0) & (local_w < words_per_shard)
    gw = jnp.clip(local_w, 0, words_per_shard - 1)
    b = acc.shape[0]
    if acc_word.shape[0] > 0:
        sel = (acc[:, gw] & acc_mask) != 0  # [b, n_br]
        sel = jnp.where(in_shard[None, :], sel, False)
        sel = jax.lax.psum(sel.astype(jnp.uint8), "rp")
        matched = jnp.zeros((b, n_rules), dtype=jnp.uint8)
        matched = matched.at[:, branch_rule].max((sel > 0).astype(jnp.uint8))
    else:
        matched = jax.lax.psum(
            jnp.zeros((b, n_rules), dtype=jnp.uint8), "rp"
        )
    matched = matched | always_match.astype(jnp.uint8)[None, :]
    empty = (lens_local == 0)[:, None].astype(jnp.uint8)
    matched = matched | (empty_only.astype(jnp.uint8)[None, :] * empty)
    return matched


def sharded_match_fn(compiled: CompiledRules, mesh: Mesh):
    """Build the jitted multi-device match step (XLA-scan body).

    Returns fn(params, cls_ids [B, L], lens [B]) → matched [B, n_rules]
    uint8, with B divisible by the dp axis size and compiled.n_shards equal
    to the rp axis size.
    """
    rp = mesh.shape["rp"]
    if compiled.n_shards != rp:
        raise ValueError(
            f"ruleset compiled for {compiled.n_shards} shards, mesh rp={rp}"
        )
    n_rules = compiled.n_rules
    words_per_shard = compiled.words_per_shard

    def local_step(params, cls_local, lens_local):
        # state scan over this device's word slice only
        acc = nfa_jax.nfa_scan(params, cls_local, lens_local)  # [b, W_local]
        return _extract_local(
            acc, lens_local,
            params["acc_word"], params["acc_mask"], params["branch_rule"],
            params["always_match"], params["empty_only"],
            n_rules, words_per_shard,
        )

    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(_param_specs(), P("dp", None), P("dp")),
        out_specs=P("dp", None),
        # the scan carry inside nfa_scan starts as a plain jnp.zeros; skip
        # the varying-manual-axes check rather than pcast-ing the carry
        check_vma=False,
    )
    return jax.jit(fn)


def shard_params(
    compiled: CompiledRules, mesh: Mesh
) -> Dict[str, jnp.ndarray]:
    """Device-put the match params with the mesh sharding applied."""
    params = nfa_jax.match_params(compiled)
    specs = _param_specs()
    return {
        k: jax.device_put(v, NamedSharding(mesh, specs[k]))
        for k, v in params.items()
    }


# ---- Pallas per-device body (the production kernel under the mesh) ----


def _pallas_specs() -> Dict[str, P]:
    # btab_t rows are shard-major ([ns * 4 * wps_p, C_p]), masks_t likewise
    # ([ns * wps_p, 8]): sharding axis 0 over rp hands each device exactly
    # its own shard's slab
    return {
        "btab_t": P("rp", None),
        "masks_t": P("rp", None),
        "acc_word": P(),
        "acc_mask": P(),
        "branch_rule": P(),
        "always_match": P(),
        "empty_only": P(),
    }


def shard_pallas_params(
    prep: pallas_nfa.PallasRules, mesh: Mesh
) -> Dict[str, jnp.ndarray]:
    """Device-put the kernel tensors with the mesh sharding applied."""
    params = {
        "btab_t": prep.btab_t,
        "masks_t": prep.masks_t,
        "acc_word": prep.acc_word,
        "acc_mask": prep.acc_mask,
        "branch_rule": prep.branch_rule,
        "always_match": prep.always_match,
        "empty_only": prep.empty_only,
    }
    specs = _pallas_specs()
    return {
        k: jax.device_put(v, NamedSharding(mesh, specs[k]))
        for k, v in params.items()
    }


def sharded_pallas_fn(
    prep: pallas_nfa.PallasRules,
    mesh: Mesh,
    B: int,
    L_p: int,
    block_b: int,
    interpret: bool = False,
):
    """Multi-device match step whose per-device body is the Pallas kernel.

    fn(params, cls_t [L_p, B], lens [B]) → matched [B, n_rules] uint8.
    B must be divisible by dp * block_b; prep.n_shards must equal rp.
    """
    dp, rp = mesh.shape["dp"], mesh.shape["rp"]
    if prep.n_shards != rp:
        raise ValueError(
            f"ruleset prepared for {prep.n_shards} shards, mesh rp={rp}"
        )
    if B % (dp * block_b):
        raise ValueError(
            f"batch {B} must be a multiple of dp*block_b = {dp * block_b}"
        )
    b_local = B // dp
    n_rules = prep.n_rules
    wps_p = prep.wps_p
    call = pallas_nfa._build_raw_call(
        b_local, L_p, prep.n_classes_p, 1, wps_p, block_b, interpret,
        carry=not prep.carry_free,
    )

    def local_step(params, cls_t_local, lens_local):
        lens_row = lens_local[None, :]
        maxtile = jnp.asarray(
            -(-lens_local.reshape(b_local // block_b, block_b).max(axis=1)
              // pallas_nfa._COLS_PER_STEP),
            dtype=jnp.int32,
        )
        acc_t = call(
            maxtile, cls_t_local, lens_row, params["btab_t"], params["masks_t"]
        )  # [wps_p, b_local]
        return _extract_local(
            acc_t.T, lens_local,
            params["acc_word"], params["acc_mask"], params["branch_rule"],
            params["always_match"], params["empty_only"],
            n_rules, wps_p,
        )

    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(_pallas_specs(), P(None, "dp"), P("dp")),
        out_specs=P("dp", None),
        check_vma=False,
    )
    return jax.jit(fn)


# ---- fused two-stage prefilter under the mesh ----
#
# Stage 1 (the narrow factor/always automaton) is REPLICATED: every device
# scans its dp row's line shard against the whole stage-1 NFA — it is ~5x
# narrower than the full ruleset, so replicating it costs less than any
# resharding would. The candidate gate and compaction are dp-shard-local
# (identical across the rp members of a row, so no collective is needed to
# agree). Stage 2 (the full filterable-rule NFA) stays rp-sharded exactly
# like the single-stage path and runs ONLY on the compacted candidates; the
# one psum over rp of accept bits remains the only collective in the step.


def sharded_fused_fn(
    plan,                       # prefilter.PrefilterPlan (stage2 packed rp-sharded)
    mesh: Mesh,
    B: int,
    L_p: int,
    block_b: int,
    backend: str,               # xla | pallas | pallas-interpret
    cand_frac: float = 0.125,
):
    """Multi-device fused two-stage match step.

    Returns (fn, params, K_local) where fn(params1, params2, cls, lens) →
    (bits [B, n_rules] uint8 — always-rule static flags NOT yet applied,
    n_cand [dp] int32 — per-shard candidate counts for the overflow check).
    """
    from banjax_tpu.matcher.prefilter import gate_masks

    dp, rp = mesh.shape["dp"], mesh.shape["rp"]
    if plan.stage2.n_shards != rp:
        raise ValueError(
            f"plan stage2 packed for {plan.stage2.n_shards} shards, mesh rp={rp}"
        )
    b_local = B // dp
    block = min(block_b, b_local)
    K = min(b_local, max(block, -(-int(b_local * cand_frac) // block) * block))
    n_rules = plan.n_rules
    n_filt = plan.stage2.n_rules
    n_always = plan.n_always
    a_idx = jnp.asarray(plan.a_idx, dtype=jnp.int32)
    f_idx = jnp.asarray(plan.f_idx, dtype=jnp.int32)
    pallas = backend in ("pallas", "pallas-interpret")
    interpret = backend == "pallas-interpret"

    if pallas:
        prep1 = pallas_nfa.prepare(plan.stage1)
        prep2 = pallas_nfa.prepare(plan.stage2)
        fmask_np, a_word, a_mask, a_rule = gate_masks(plan, prep1)
        wps2 = prep2.wps_p
        cols = pallas_nfa._COLS_PER_STEP
        # stage 1 may itself be packed into several shards ("auto"); the
        # replicated body runs them as the kernel's shard grid axis
        call1 = pallas_nfa._build_raw_call(
            b_local, L_p, prep1.n_classes_p, prep1.n_shards, prep1.wps_p,
            block, interpret,
            carry=not prep1.carry_free,
        )
        # stage 2: each rp member owns exactly one word slab → local ns=1
        call2 = pallas_nfa._build_raw_call(
            K, L_p, prep2.n_classes_p, 1, wps2, min(block, K), interpret,
            carry=not prep2.carry_free,
        )
        params1 = {"btab_t": prep1.btab_t, "masks_t": prep1.masks_t}
        params2 = shard_pallas_params(prep2, mesh)
    else:
        fmask_np, a_word, a_mask, a_rule = gate_masks(plan)
        wps2 = plan.stage2.words_per_shard
        params1 = nfa_jax.match_params(plan.stage1)
        params2 = shard_params(plan.stage2, mesh)
    fmask = jnp.asarray(fmask_np)
    a_word_j = jnp.asarray(a_word)
    a_mask_j = jnp.asarray(a_mask)
    a_rule_j = jnp.asarray(a_rule)

    def _gate_and_compact(acc1, cls_rows_local, lens_local):
        """acc1 [b, W1]; cls_rows_local [b, L_p] → candidate gather."""
        cand = (acc1 & fmask[None, :]).max(axis=1) > 0
        n_cand = jnp.sum(cand.astype(jnp.int32))
        (idx,) = jnp.nonzero(cand, size=K, fill_value=0)
        valid = jax.lax.iota(jnp.int32, K) < n_cand
        cls2 = jnp.take(cls_rows_local, idx, axis=0)
        lens2 = jnp.where(valid, jnp.take(lens_local, idx), 0)
        return idx, valid, n_cand, cls2, lens2

    def _always_bits(acc1):
        """[b, n_always] uint8 from stage-1 accept words (dynamic part)."""
        b = acc1.shape[0]
        ab = jnp.zeros((b, max(1, n_always)), dtype=jnp.uint8)
        if n_always and a_word_j.shape[0] > 0:
            sel = (acc1[:, a_word_j] & a_mask_j) != 0  # [b, n_abr]
            ab = ab.at[:, a_rule_j].max(sel.astype(jnp.uint8))
        return ab

    def _merge(idx, valid, m2, ab, b):
        m2 = m2 & (valid[:, None] * jnp.uint8(0xFF))
        filt = jnp.zeros((b, n_filt), dtype=jnp.uint8).at[idx].max(m2)
        bits = jnp.zeros((b, n_rules), dtype=jnp.uint8)
        if n_always:
            bits = bits.at[:, a_idx].set(ab[:, :n_always])
        bits = bits.at[:, f_idx].set(filt)
        return bits

    if pallas:

        def local_step(p1, p2, cls_t_local, lens_local):
            lens_row = lens_local[None, :]
            maxtile1 = jnp.asarray(
                -(-lens_local.reshape(b_local // block, block).max(axis=1)
                  // cols),
                dtype=jnp.int32,
            )
            acc1 = call1(
                maxtile1, cls_t_local, lens_row, p1["btab_t"], p1["masks_t"]
            ).T  # [b, W1p]
            idx, valid, n_cand, cls2_t, lens2 = _gate_and_compact(
                acc1, cls_t_local.T, lens_local
            )
            blk2 = min(block, K)
            maxtile2 = jnp.asarray(
                -(-lens2.reshape(K // blk2, blk2).max(axis=1) // cols),
                dtype=jnp.int32,
            )
            acc2 = call2(
                maxtile2, cls2_t.T, lens2[None, :],
                p2["btab_t"], p2["masks_t"],
            ).T  # [K, wps2]
            m2 = _extract_local(
                acc2, lens2,
                p2["acc_word"], p2["acc_mask"], p2["branch_rule"],
                p2["always_match"], p2["empty_only"],
                n_filt, wps2,
            )
            bits = _merge(idx, valid, m2, _always_bits(acc1), b_local)
            return bits, n_cand[None]

        in_specs = (
            {"btab_t": P(), "masks_t": P()}, _pallas_specs(),
            P(None, "dp"), P("dp"),
        )
    else:

        def local_step(p1, p2, cls_local, lens_local):
            acc1 = nfa_jax.nfa_scan(p1, cls_local, lens_local)  # [b, W1]
            idx, valid, n_cand, cls2, lens2 = _gate_and_compact(
                acc1, cls_local, lens_local
            )
            acc2 = nfa_jax.nfa_scan(p2, cls2, lens2)            # [K, W2l]
            m2 = _extract_local(
                acc2, lens2,
                p2["acc_word"], p2["acc_mask"], p2["branch_rule"],
                p2["always_match"], p2["empty_only"],
                n_filt, wps2,
            )
            bits = _merge(idx, valid, m2, _always_bits(acc1), b_local)
            return bits, n_cand[None]

        p1_specs = {k: P() for k in params1}
        in_specs = (p1_specs, _param_specs(), P("dp", None), P("dp"))

    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P("dp", None), P("dp")),
        check_vma=False,
    )
    return jax.jit(fn), (params1, params2), K


class ShardedMatchBackend:
    """Batch-level mesh matcher: the drop-in device backend for TpuMatcher.

    match_bits pads/permutes an encoded batch onto the dp axis (length-
    sorted round-robin so every device gets a balanced mix of line lengths
    for the kernel's tile skip), runs the sharded device step, and returns
    the bitmap in the caller's original line order.
    """

    def __init__(
        self,
        compiled: CompiledRules,
        mesh: Mesh,
        max_len: int,
        backend: str = "pallas",   # pallas | pallas-interpret | xla
        block_b: int = 128,
        plan=None,                 # prefilter.PrefilterPlan (stage2 rp-packed)
        cand_frac: float = 0.125,
        health=None,               # resilience.health.ComponentHealth
    ):
        self.health = health
        self.mesh = mesh
        self.dp = mesh.shape["dp"]
        self.rp = mesh.shape["rp"]
        self.backend = backend
        self.n_rules = compiled.n_rules
        self.max_len = max_len
        self.block_b = block_b
        self.cand_frac = cand_frac
        self._fns: Dict[Tuple[int, int], object] = {}
        self._fused_fns: Dict[Tuple[int, int], object] = {}
        self.plan = plan
        # counters for observability: how often the fused path ran vs fell
        # back to the single-stage sharded NFA (candidate overflow)
        self.fused_batches = 0
        self.fallback_batches = 0
        # sharded submit/drain latency (metrics line): dispatch wall time,
        # the per-shard d2h pulls of the last drain, and their EWMAs
        self.submit_ms_ewma: Optional[float] = None
        self.merge_ms_ewma: Optional[float] = None
        self.last_shard_merge_ms: list = []
        if backend == "xla":
            self._prep = None
            self._params = shard_params(compiled, mesh)
            self._compiled = compiled
        else:
            self._prep = pallas_nfa.prepare(compiled)
            self._params = shard_pallas_params(self._prep, mesh)
            self._compiled = compiled

    def _fn(self, B: int, L_p: int):
        key = (B, L_p)
        fn = self._fns.get(key)
        if fn is None:
            if self.backend == "xla":
                fn = sharded_match_fn(self._compiled, self.mesh)
            else:
                fn = sharded_pallas_fn(
                    self._prep, self.mesh, B, L_p, self.block_b,
                    interpret=self.backend == "pallas-interpret",
                )
            self._fns[key] = fn
        return fn

    def _fused(self, B: int, L_p: int):
        key = (B, L_p)
        hit = self._fused_fns.get(key)
        if hit is None:
            hit = sharded_fused_fn(
                self.plan, self.mesh, B, L_p, self.block_b, self.backend,
                cand_frac=self.cand_frac,
            )
            self._fused_fns[key] = hit
        return hit

    def _dispatch(self, fn, params, cls_dev, lens_dev):
        if self.backend == "xla":
            return fn(params, jnp.asarray(cls_dev), jnp.asarray(lens_dev))
        cls_t = np.ascontiguousarray(cls_dev.T)
        return fn(params, jnp.asarray(cls_t), jnp.asarray(lens_dev))

    @staticmethod
    def _async_copy(arr) -> None:
        try:
            arr.copy_to_host_async()
        except AttributeError:
            pass

    def _ewma(self, attr: str, value_ms: float) -> None:
        prev = getattr(self, attr)
        setattr(
            self, attr,
            value_ms if prev is None else prev + 0.2 * (value_ms - prev),
        )

    def submit(self, cls_ids: np.ndarray, lens: np.ndarray) -> dict:
        """Dispatch the sharded device step for one batch WITHOUT forcing
        any device→host transfer — the streaming pipeline's submit stage.
        Returns a pend dict for collect(); the async host copies are
        already in flight so collect()'s pull overlaps later submits."""
        t0 = time.perf_counter()
        cls_ids = np.asarray(cls_ids, dtype=np.int32)
        lens = np.asarray(lens, dtype=np.int32)
        B, L = cls_ids.shape
        # bucket the padded batch to power-of-two multiples of dp*block_b so
        # varying batch sizes share a bounded set of compiled programs
        chunk = self.dp * self.block_b
        Bp = chunk
        while Bp < B:
            Bp <<= 1

        # trim the scan to the longest real line (pad columns can't change
        # state); power-of-two buckets bound the jitted L_p variants
        max_len = int(lens.max()) if B else 0
        L_cap = pallas_nfa._pad_to(L, pallas_nfa._COLS_PER_STEP)
        L_p = 32
        while L_p < max_len:
            L_p <<= 1
        L_p = max(pallas_nfa._COLS_PER_STEP, min(L_cap, L_p))

        # length-sorted round-robin over dp: device d gets sorted lines
        # d, d+dp, d+2*dp, ... — balanced tile-skip work per device
        order = np.argsort(lens, kind="stable")
        perm = np.empty(Bp, dtype=np.int64)
        rows_per_dev = Bp // self.dp
        pos = 0
        for d in range(self.dp):
            idx = np.arange(d, Bp, self.dp)
            perm[pos : pos + rows_per_dev] = idx
            pos += rows_per_dev
        # perm[k] = which padded-sorted row device-major slot k takes
        cls_sorted = np.zeros((Bp, L_p), dtype=np.int32)
        cls_sorted[:B, : min(L, L_p)] = cls_ids[order, : min(L, L_p)]
        lens_sorted = np.zeros(Bp, dtype=np.int32)
        lens_sorted[:B] = lens[order]
        cls_dev = cls_sorted[perm]
        lens_dev = lens_sorted[perm]

        pend = {
            "B": B, "Bp": Bp, "L_p": L_p, "order": order, "perm": perm,
            "lens_dev": lens_dev, "cls_dev": cls_dev, "fused": False,
            "h2d_bytes": cls_dev.nbytes + lens_dev.nbytes, "d2h_bytes": 0,
        }
        fused = None
        if self.plan is not None:
            # fused two-stage: stage-1 gate per dp shard, stage-2 on the
            # compacted candidates only; per-shard candidate overflow
            # (adversarial all-matching traffic) falls back to the
            # single-stage sharded NFA — never under-matches
            try:
                fused = self._fused(Bp, L_p)
            except pallas_nfa.PallasUnsupported as e:
                # e.g. stage-1 word alignment pushed a shard past the VMEM
                # budget: a kernel-shape refusal at first use must degrade
                # to the single-stage path, not kill consume_lines
                import logging

                logging.getLogger(__name__).info(
                    "fused mesh prefilter unavailable (%s); single-stage", e
                )
                self.plan = None
        if fused is not None:
            fn, params, K = fused
            with trace.span("mesh-submit",
                            args={"dp": self.dp, "fused": True}):
                bits_d, n_cand = self._dispatch(
                    lambda p, c, ln: fn(*p, c, ln), params, cls_dev, lens_dev
                )
                self._async_copy(n_cand)
                self._async_copy(bits_d)
            pend.update(fused=True, K=K, bits_d=bits_d, n_cand=n_cand)
            if self.health is not None:
                self.health.beat()
        else:
            fn = self._fn(Bp, L_p)
            with trace.span("mesh-submit",
                            args={"dp": self.dp, "fused": False}):
                out_d = self._dispatch(fn, self._params, cls_dev, lens_dev)
                self._async_copy(out_d)
            pend["out_d"] = out_d
        self._ewma("submit_ms_ewma", (time.perf_counter() - t0) * 1e3)
        return pend

    def collect(self, pend: dict) -> np.ndarray:
        """Force a submit()ted batch: pull each dp shard's rows, merge them
        back into the caller's line order, apply the host-side always-rule
        flags.  The per-shard pull latencies land in last_shard_merge_ms
        (metrics: MeshShardMergeMsMax)."""
        t0 = time.perf_counter()
        B, Bp = pend["B"], pend["Bp"]
        order, perm = pend["order"], pend["perm"]
        out = None
        if pend["fused"]:
            if int(np.asarray(pend["n_cand"]).max()) <= pend["K"]:
                out = self._pull_shards(pend["bits_d"])
                self.fused_batches += 1
                if self.health is not None:
                    self.health.ok()
                # always-rule static flags (host-applied, like the
                # single-device collect())
                plan = self.plan
                if plan is not None and plan.n_always:
                    aw = np.asarray(plan.stage1.always_match[: plan.n_always])
                    ae = np.asarray(plan.stage1.empty_only[: plan.n_always])
                    if aw.any():
                        out[:, plan.a_idx[aw]] = 1
                    if ae.any():
                        empty_rows = np.flatnonzero(pend["lens_dev"] == 0)
                        out[np.ix_(empty_rows, plan.a_idx[ae])] = 1
            else:
                self.fallback_batches += 1
                if self.health is not None:
                    # correctness-preserving but slower: the single-stage
                    # sharded NFA reruns the whole batch
                    self.health.degraded(
                        f"fused prefilter overflow x{self.fallback_batches}; "
                        "single-stage rerun"
                    )
        if out is None:
            if "out_d" not in pend:
                fn = self._fn(Bp, pend["L_p"])
                pend["out_d"] = self._dispatch(
                    fn, self._params, pend["cls_dev"], pend["lens_dev"]
                )
            out = self._pull_shards(pend["out_d"])
        pend["d2h_bytes"] += out.nbytes

        # undo the device permutation, then the length sort
        unperm = np.empty(Bp, dtype=np.int64)
        unperm[perm] = np.arange(Bp)
        out_sorted = out[unperm][:B]
        unsorted = np.empty_like(out_sorted)
        unsorted[order] = out_sorted
        self._ewma("merge_ms_ewma", (time.perf_counter() - t0) * 1e3)
        return unsorted

    def _pull_shards(self, arr) -> np.ndarray:
        """Per-shard device→host pull into one writable host array: each dp
        member's row block lands at its own index (rp replicas of the same
        rows are pulled once), timed per shard."""
        self.last_shard_merge_ms = []
        try:
            shards = list(arr.addressable_shards)
        except (AttributeError, TypeError):
            shards = []
        if not shards:
            t0 = time.perf_counter()
            out = np.array(arr)
            self.last_shard_merge_ms.append(
                (time.perf_counter() - t0) * 1e3
            )
            return out
        out = np.empty(arr.shape, dtype=arr.dtype)
        seen = set()
        for sh in shards:
            idx = sh.index
            key = tuple(
                (sl.start, sl.stop, sl.step) if isinstance(sl, slice) else sl
                for sl in idx
            )
            if key in seen:
                continue  # an rp replica of rows already merged
            seen.add(key)
            t0 = time.perf_counter()
            # one span per device shard's d2h pull (child of the ambient
            # collect/drain span when a traced pipeline batch drives this)
            with trace.span("mesh-shard-pull",
                            args={"shard": len(seen) - 1}):
                data = np.asarray(sh.data)
            self.last_shard_merge_ms.append((time.perf_counter() - t0) * 1e3)
            out[idx] = data
        return out

    def match_bits(self, cls_ids: np.ndarray, lens: np.ndarray) -> np.ndarray:
        """[B, L] encoded lines → [B, n_rules] uint8, any B (dp remainder
        handled by padding; output order matches input order).  The
        synchronous convenience form of submit()/collect()."""
        return self.collect(self.submit(cls_ids, lens))
