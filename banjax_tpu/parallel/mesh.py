"""Device-mesh sharding for the batched NFA matcher.

The reference scales horizontally by running N independent banjax+nginx
edges with no shared state (SURVEY.md §2.3); the TPU-native equivalent is a
`jax.sharding.Mesh` over two axes:

  * `dp` — data parallel over the line batch: each device classifies a
    shard of the encoded lines (the "log shards across cores" strategy of
    BASELINE.json's "one pmap'd pass").
  * `rp` — rule parallel over the packed NFA word axis: each device holds a
    slice of the transition masks (the VMEM budget constraint of SURVEY.md
    §7.3 hard part 3). rulec lays branches out so none straddles an `rp`
    shard boundary, so the in-shard packed shift never needs a cross-device
    carry; the only collective is one `psum` of accept bits over `rp`,
    riding ICI.

Windows/Decisions stay host-side (runner.py), so this module is the entire
multi-chip device step — the thing `__graft_entry__.dryrun_multichip`
compiles and runs on an N-virtual-device mesh.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

from banjax_tpu.matcher import nfa_jax
from banjax_tpu.matcher.rulec import CompiledRules


def make_mesh(n_devices: int, rp: int = 1) -> Mesh:
    """Mesh of shape (dp = n_devices // rp, rp)."""
    if n_devices % rp != 0:
        raise ValueError(f"n_devices {n_devices} not divisible by rp {rp}")
    devices = np.array(jax.devices()[:n_devices]).reshape(n_devices // rp, rp)
    return Mesh(devices, axis_names=("dp", "rp"))


def _param_specs() -> Dict[str, P]:
    return {
        "b_table": P(None, "rp"),
        "shift_in": P("rp"),
        "inject_always": P("rp"),
        "inject_start": P("rp"),
        "selfloop": P("rp"),
        "accept_any": P("rp"),
        "accept_end": P("rp"),
        # branch/extraction arrays are replicated; each rp member selects its
        # own branches by word-index range
        "acc_word": P(),
        "acc_mask": P(),
        "branch_rule": P(),
        "always_match": P(),
        "empty_only": P(),
    }


def sharded_match_fn(compiled: CompiledRules, mesh: Mesh):
    """Build the jitted multi-device match step.

    Returns fn(params, cls_ids [B, L], lens [B]) → matched [B, n_rules]
    uint8, with B divisible by the dp axis size and compiled.n_shards equal
    to the rp axis size.
    """
    rp = mesh.shape["rp"]
    if compiled.n_shards != rp:
        raise ValueError(
            f"ruleset compiled for {compiled.n_shards} shards, mesh rp={rp}"
        )
    n_rules = compiled.n_rules
    words_per_shard = compiled.words_per_shard

    def local_step(params, cls_local, lens_local):
        # state scan over this device's word slice only
        acc = nfa_jax.nfa_scan(params, cls_local, lens_local)  # [b, W_local]
        shard = jax.lax.axis_index("rp")
        local_w = params["acc_word"] - shard * words_per_shard
        in_shard = (local_w >= 0) & (local_w < words_per_shard)
        gw = jnp.clip(local_w, 0, words_per_shard - 1)
        sel = (acc[:, gw] & params["acc_mask"]) != 0  # [b, n_br]
        sel = jnp.where(in_shard[None, :], sel, False)
        # combine accept bits across the rule-parallel axis (ICI collective)
        sel = jax.lax.psum(sel.astype(jnp.uint8), "rp")
        b = cls_local.shape[0]
        matched = jnp.zeros((b, n_rules), dtype=jnp.uint8)
        if compiled.acc_word.shape[0] > 0:
            matched = matched.at[:, params["branch_rule"]].max(
                (sel > 0).astype(jnp.uint8)
            )
        matched = matched | params["always_match"].astype(jnp.uint8)[None, :]
        empty = (lens_local == 0)[:, None].astype(jnp.uint8)
        matched = matched | (params["empty_only"].astype(jnp.uint8)[None, :] * empty)
        return matched

    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(_param_specs(), P("dp", None), P("dp")),
        out_specs=P("dp", None),
        # the scan carry inside nfa_scan starts as a plain jnp.zeros; skip
        # the varying-manual-axes check rather than pcast-ing the carry
        check_vma=False,
    )
    return jax.jit(fn)


def shard_params(
    compiled: CompiledRules, mesh: Mesh
) -> Dict[str, jnp.ndarray]:
    """Device-put the match params with the mesh sharding applied."""
    params = nfa_jax.match_params(compiled)
    specs = _param_specs()
    return {
        k: jax.device_put(v, jax.sharding.NamedSharding(mesh, specs[k]))
        for k, v in params.items()
    }
