"""Device-mesh sharding for the batched NFA matcher.

The reference scales horizontally by running N independent banjax+nginx
edges with no shared state (SURVEY.md §2.3); the TPU-native equivalent is a
`jax.sharding.Mesh` over two axes:

  * `dp` — data parallel over the line batch: each device classifies a
    shard of the encoded lines (the "log shards across cores" strategy of
    BASELINE.json's "one pmap'd pass").
  * `rp` — rule parallel over the packed NFA word axis: each device holds a
    slice of the transition masks (the VMEM budget constraint of SURVEY.md
    §7.3 hard part 3). rulec lays branches out so none straddles an `rp`
    shard boundary, so the in-shard packed shift never needs a cross-device
    carry; the only collective is one `psum` of accept bits over `rp`,
    riding ICI.

The per-device body is the SAME Pallas kernel the single-chip product path
runs (matcher/kernels/nfa_match.py) — each rp member scans its own word
slab with a one-shard grid; `backend="xla"` swaps in the nfa_jax scan and
`backend="pallas-interpret"` runs the kernel as plain JAX (the CPU-mesh CI
and dryrun path). `ShardedMatchBackend` is the batch-level wrapper
TpuMatcher plugs into `_match_bits` when a mesh is configured.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

from banjax_tpu.matcher import nfa_jax
from banjax_tpu.matcher.kernels import nfa_match as pallas_nfa
from banjax_tpu.matcher.rulec import CompiledRules


def make_mesh(n_devices: int, rp: int = 1) -> Mesh:
    """Mesh of shape (dp = n_devices // rp, rp)."""
    if n_devices % rp != 0:
        raise ValueError(f"n_devices {n_devices} not divisible by rp {rp}")
    devices = np.array(jax.devices()[:n_devices]).reshape(n_devices // rp, rp)
    return Mesh(devices, axis_names=("dp", "rp"))


def _param_specs() -> Dict[str, P]:
    return {
        "b_table": P(None, "rp"),
        "shift_in": P("rp"),
        "inject_always": P("rp"),
        "inject_start": P("rp"),
        "selfloop": P("rp"),
        "accept_any": P("rp"),
        "accept_end": P("rp"),
        # branch/extraction arrays are replicated; each rp member selects its
        # own branches by word-index range
        "acc_word": P(),
        "acc_mask": P(),
        "branch_rule": P(),
        "always_match": P(),
        "empty_only": P(),
    }


def _extract_local(
    acc,                 # [b, W_local] uint32 — this shard's accept words
    lens_local,          # [b] int32
    acc_word, acc_mask, branch_rule, always_match, empty_only,
    n_rules: int,
    words_per_shard: int,
):
    """Shard-local accept extraction + the rp psum combine (shared by the
    XLA and Pallas bodies — the only collective in the device step)."""
    shard = jax.lax.axis_index("rp")
    local_w = acc_word - shard * words_per_shard
    in_shard = (local_w >= 0) & (local_w < words_per_shard)
    gw = jnp.clip(local_w, 0, words_per_shard - 1)
    b = acc.shape[0]
    if acc_word.shape[0] > 0:
        sel = (acc[:, gw] & acc_mask) != 0  # [b, n_br]
        sel = jnp.where(in_shard[None, :], sel, False)
        sel = jax.lax.psum(sel.astype(jnp.uint8), "rp")
        matched = jnp.zeros((b, n_rules), dtype=jnp.uint8)
        matched = matched.at[:, branch_rule].max((sel > 0).astype(jnp.uint8))
    else:
        matched = jax.lax.psum(
            jnp.zeros((b, n_rules), dtype=jnp.uint8), "rp"
        )
    matched = matched | always_match.astype(jnp.uint8)[None, :]
    empty = (lens_local == 0)[:, None].astype(jnp.uint8)
    matched = matched | (empty_only.astype(jnp.uint8)[None, :] * empty)
    return matched


def sharded_match_fn(compiled: CompiledRules, mesh: Mesh):
    """Build the jitted multi-device match step (XLA-scan body).

    Returns fn(params, cls_ids [B, L], lens [B]) → matched [B, n_rules]
    uint8, with B divisible by the dp axis size and compiled.n_shards equal
    to the rp axis size.
    """
    rp = mesh.shape["rp"]
    if compiled.n_shards != rp:
        raise ValueError(
            f"ruleset compiled for {compiled.n_shards} shards, mesh rp={rp}"
        )
    n_rules = compiled.n_rules
    words_per_shard = compiled.words_per_shard

    def local_step(params, cls_local, lens_local):
        # state scan over this device's word slice only
        acc = nfa_jax.nfa_scan(params, cls_local, lens_local)  # [b, W_local]
        return _extract_local(
            acc, lens_local,
            params["acc_word"], params["acc_mask"], params["branch_rule"],
            params["always_match"], params["empty_only"],
            n_rules, words_per_shard,
        )

    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(_param_specs(), P("dp", None), P("dp")),
        out_specs=P("dp", None),
        # the scan carry inside nfa_scan starts as a plain jnp.zeros; skip
        # the varying-manual-axes check rather than pcast-ing the carry
        check_vma=False,
    )
    return jax.jit(fn)


def shard_params(
    compiled: CompiledRules, mesh: Mesh
) -> Dict[str, jnp.ndarray]:
    """Device-put the match params with the mesh sharding applied."""
    params = nfa_jax.match_params(compiled)
    specs = _param_specs()
    return {
        k: jax.device_put(v, NamedSharding(mesh, specs[k]))
        for k, v in params.items()
    }


# ---- Pallas per-device body (the production kernel under the mesh) ----


def _pallas_specs() -> Dict[str, P]:
    # btab_t rows are shard-major ([ns * 4 * wps_p, C_p]), masks_t likewise
    # ([ns * wps_p, 8]): sharding axis 0 over rp hands each device exactly
    # its own shard's slab
    return {
        "btab_t": P("rp", None),
        "masks_t": P("rp", None),
        "acc_word": P(),
        "acc_mask": P(),
        "branch_rule": P(),
        "always_match": P(),
        "empty_only": P(),
    }


def shard_pallas_params(
    prep: pallas_nfa.PallasRules, mesh: Mesh
) -> Dict[str, jnp.ndarray]:
    """Device-put the kernel tensors with the mesh sharding applied."""
    params = {
        "btab_t": prep.btab_t,
        "masks_t": prep.masks_t,
        "acc_word": prep.acc_word,
        "acc_mask": prep.acc_mask,
        "branch_rule": prep.branch_rule,
        "always_match": prep.always_match,
        "empty_only": prep.empty_only,
    }
    specs = _pallas_specs()
    return {
        k: jax.device_put(v, NamedSharding(mesh, specs[k]))
        for k, v in params.items()
    }


def sharded_pallas_fn(
    prep: pallas_nfa.PallasRules,
    mesh: Mesh,
    B: int,
    L_p: int,
    block_b: int,
    interpret: bool = False,
):
    """Multi-device match step whose per-device body is the Pallas kernel.

    fn(params, cls_t [L_p, B], lens [B]) → matched [B, n_rules] uint8.
    B must be divisible by dp * block_b; prep.n_shards must equal rp.
    """
    dp, rp = mesh.shape["dp"], mesh.shape["rp"]
    if prep.n_shards != rp:
        raise ValueError(
            f"ruleset prepared for {prep.n_shards} shards, mesh rp={rp}"
        )
    if B % (dp * block_b):
        raise ValueError(
            f"batch {B} must be a multiple of dp*block_b = {dp * block_b}"
        )
    b_local = B // dp
    n_rules = prep.n_rules
    wps_p = prep.wps_p
    call = pallas_nfa._build_raw_call(
        b_local, L_p, prep.n_classes_p, 1, wps_p, block_b, interpret
    )

    def local_step(params, cls_t_local, lens_local):
        lens_row = lens_local[None, :]
        maxtile = jnp.asarray(
            -(-lens_local.reshape(b_local // block_b, block_b).max(axis=1)
              // pallas_nfa._COLS_PER_STEP),
            dtype=jnp.int32,
        )
        acc_t = call(
            maxtile, cls_t_local, lens_row, params["btab_t"], params["masks_t"]
        )  # [wps_p, b_local]
        return _extract_local(
            acc_t.T, lens_local,
            params["acc_word"], params["acc_mask"], params["branch_rule"],
            params["always_match"], params["empty_only"],
            n_rules, wps_p,
        )

    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(_pallas_specs(), P(None, "dp"), P("dp")),
        out_specs=P("dp", None),
        check_vma=False,
    )
    return jax.jit(fn)


class ShardedMatchBackend:
    """Batch-level mesh matcher: the drop-in device backend for TpuMatcher.

    match_bits pads/permutes an encoded batch onto the dp axis (length-
    sorted round-robin so every device gets a balanced mix of line lengths
    for the kernel's tile skip), runs the sharded device step, and returns
    the bitmap in the caller's original line order.
    """

    def __init__(
        self,
        compiled: CompiledRules,
        mesh: Mesh,
        max_len: int,
        backend: str = "pallas",   # pallas | pallas-interpret | xla
        block_b: int = 128,
    ):
        self.mesh = mesh
        self.dp = mesh.shape["dp"]
        self.rp = mesh.shape["rp"]
        self.backend = backend
        self.n_rules = compiled.n_rules
        self.max_len = max_len
        self.block_b = block_b
        self._fns: Dict[Tuple[int, int], object] = {}
        if backend == "xla":
            self._prep = None
            self._params = shard_params(compiled, mesh)
            self._compiled = compiled
        else:
            self._prep = pallas_nfa.prepare(compiled)
            self._params = shard_pallas_params(self._prep, mesh)
            self._compiled = compiled

    def _fn(self, B: int, L_p: int):
        key = (B, L_p)
        fn = self._fns.get(key)
        if fn is None:
            if self.backend == "xla":
                fn = sharded_match_fn(self._compiled, self.mesh)
            else:
                fn = sharded_pallas_fn(
                    self._prep, self.mesh, B, L_p, self.block_b,
                    interpret=self.backend == "pallas-interpret",
                )
            self._fns[key] = fn
        return fn

    def match_bits(self, cls_ids: np.ndarray, lens: np.ndarray) -> np.ndarray:
        """[B, L] encoded lines → [B, n_rules] uint8, any B (dp remainder
        handled by padding; output order matches input order)."""
        cls_ids = np.asarray(cls_ids, dtype=np.int32)
        lens = np.asarray(lens, dtype=np.int32)
        B, L = cls_ids.shape
        # bucket the padded batch to power-of-two multiples of dp*block_b so
        # varying batch sizes share a bounded set of compiled programs
        chunk = self.dp * self.block_b
        Bp = chunk
        while Bp < B:
            Bp <<= 1

        # trim the scan to the longest real line (pad columns can't change
        # state); power-of-two buckets bound the jitted L_p variants
        max_len = int(lens.max()) if B else 0
        L_cap = pallas_nfa._pad_to(L, pallas_nfa._COLS_PER_STEP)
        L_p = 32
        while L_p < max_len:
            L_p <<= 1
        L_p = max(pallas_nfa._COLS_PER_STEP, min(L_cap, L_p))

        # length-sorted round-robin over dp: device d gets sorted lines
        # d, d+dp, d+2*dp, ... — balanced tile-skip work per device
        order = np.argsort(lens, kind="stable")
        perm = np.empty(Bp, dtype=np.int64)
        rows_per_dev = Bp // self.dp
        pos = 0
        for d in range(self.dp):
            idx = np.arange(d, Bp, self.dp)
            perm[pos : pos + rows_per_dev] = idx
            pos += rows_per_dev
        # perm[k] = which padded-sorted row device-major slot k takes
        cls_sorted = np.zeros((Bp, L_p), dtype=np.int32)
        cls_sorted[:B, : min(L, L_p)] = cls_ids[order, : min(L, L_p)]
        lens_sorted = np.zeros(Bp, dtype=np.int32)
        lens_sorted[:B] = lens[order]
        cls_dev = cls_sorted[perm]
        lens_dev = lens_sorted[perm]

        fn = self._fn(Bp, L_p)
        if self.backend == "xla":
            out = np.asarray(
                fn(self._params, jnp.asarray(cls_dev), jnp.asarray(lens_dev))
            )
        else:
            cls_t = np.ascontiguousarray(cls_dev.T)
            out = np.asarray(
                fn(self._params, jnp.asarray(cls_t), jnp.asarray(lens_dev))
            )

        # undo the device permutation, then the length sort
        unperm = np.empty(Bp, dtype=np.int64)
        unperm[perm] = np.arange(Bp)
        out_sorted = out[unperm][:B]
        unsorted = np.empty_like(out_sorted)
        unsorted[order] = out_sorted
        return unsorted
