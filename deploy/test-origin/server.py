"""Fake upstream origin for the compose harness: echoes the requested URL.

Equivalent of the reference's hello-world test origin
(/root/reference/supporting-containers/test-origin/hello-world.go:15-33):
/hello says hello, everything else gets a 404 page naming the requested
path, so end-to-end tests can assert which URL reached the origin.
"""

import datetime

from aiohttp import web


async def hello(request: web.Request) -> web.Response:
    return web.Response(text="hello!\n")


async def catch_all(request: web.Request) -> web.Response:
    now = datetime.datetime.now(datetime.timezone.utc).strftime("%H:%M:%S")
    page = (
        "<html><head><title>banjax-tpu test-origin</title>"
        "<style>body{padding:2em;background-color:#ecece2;}</style></head>"
        f"<body><h1>Requested URL: {request.path}</h1>"
        f"banjax-tpu test-origin @ {now} UTC+0</body></html>"
    )
    return web.Response(
        status=404, text=page, content_type="text/html",
        headers={"Cache-Control": "no-cache"},
    )


def make_app() -> web.Application:
    app = web.Application()
    app.router.add_get("/hello", hello)
    app.router.add_route("*", "/{tail:.*}", catch_all)
    return app


if __name__ == "__main__":
    web.run_app(make_app(), host="0.0.0.0", port=8080)
