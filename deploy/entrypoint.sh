#!/bin/sh
# banjax-tpu container entrypoint.
#
# BANJAX_CONFIG       config file path (default /etc/banjax/banjax-config.yaml)
# BANJAX_DEBUG=1      verbose per-line/per-request logging
# BANJAX_STANDALONE=1 standalone-testing mode (no nginx: fake the X-* headers,
#                     self-write the access log, skip ipset)
# BANJAX_DEV=1        rebuild-on-save dev loop (deploy/dev-reload.py): restart
#                     on source change, SIGHUP on config change — the
#                     reference's air-based live rebuild (.air.toml)
set -e

CONFIG="${BANJAX_CONFIG:-/etc/banjax/banjax-config.yaml}"
ARGS="-config-file $CONFIG"
[ -n "$BANJAX_DEBUG" ] && ARGS="$ARGS -debug"
[ -n "$BANJAX_STANDALONE" ] && ARGS="$ARGS -standalone-testing"

if [ -n "$BANJAX_DEV" ]; then
    exec python /opt/banjax-tpu/deploy/dev-reload.py -- $ARGS
fi
exec python -m banjax_tpu.cli $ARGS
