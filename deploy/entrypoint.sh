#!/bin/sh
# banjax-tpu container entrypoint.
#
# BANJAX_CONFIG       config file path (default /etc/banjax/banjax-config.yaml)
# BANJAX_DEBUG=1      verbose per-line/per-request logging
# BANJAX_STANDALONE=1 standalone-testing mode (no nginx: fake the X-* headers,
#                     self-write the access log, skip ipset)
set -e

CONFIG="${BANJAX_CONFIG:-/etc/banjax/banjax-config.yaml}"
ARGS="-config-file $CONFIG"
[ -n "$BANJAX_DEBUG" ] && ARGS="$ARGS -debug"
[ -n "$BANJAX_STANDALONE" ] && ARGS="$ARGS -standalone-testing"

exec python -m banjax_tpu.cli $ARGS
