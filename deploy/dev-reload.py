#!/usr/bin/env python
"""Rebuild-on-save dev loop — the reference's `.air.toml` + entrypoint dance
(air rebuilds the Go binary when sources change; reference entrypoint.sh:3-7)
translated to the Python runtime: watch the source tree, restart the server
on change, SIGHUP it when only the config file changed (hot reload instead
of a restart, matching the product's own reload path).

Stdlib-only (mtime polling — inotify isn't portable into slim containers):

    python deploy/dev-reload.py -- -config-file deploy/banjax-config.yaml \
        -standalone-testing

or in the container via BANJAX_DEV=1 (see entrypoint.sh).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

POLL_SECONDS = 0.7
WATCH_EXTS = {".py", ".html", ".c"}


def _snapshot(root: str):
    out = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [
            d for d in dirnames
            if d not in {"__pycache__", ".git", "node_modules", "logs"}
        ]
        for f in filenames:
            if os.path.splitext(f)[1] in WATCH_EXTS:
                p = os.path.join(dirpath, f)
                try:
                    out[p] = os.stat(p).st_mtime_ns
                except OSError:
                    pass
    return out


def main() -> None:
    args = sys.argv[1:]
    if args and args[0] == "--":
        args = args[1:]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(repo, "banjax_tpu")
    config_file = None
    for i, a in enumerate(args):
        if a == "-config-file" and i + 1 < len(args):
            config_file = os.path.abspath(args[i + 1])

    cmd = [sys.executable, "-m", "banjax_tpu.cli", *args]
    proc = None

    def cfg_mtime(prev: int = 0) -> int:
        # editors replace files atomically; a momentarily-missing config
        # must not kill the watcher
        if not config_file:
            return 0
        try:
            return os.stat(config_file).st_mtime_ns
        except OSError:
            return prev

    try:
        while True:
            snap = _snapshot(src)
            cfg_m = cfg_mtime()
            print(f"[dev-reload] starting: {' '.join(cmd)}", flush=True)
            proc = subprocess.Popen(cmd, cwd=repo)
            while True:
                time.sleep(POLL_SECONDS)
                if proc.poll() is not None:
                    print(
                        f"[dev-reload] server exited rc={proc.returncode}; "
                        "restarting after next change", flush=True,
                    )
                    # wait for a SOURCE OR CONFIG change before relaunching
                    # a crash-looper (a config typo crashes the server; the
                    # fix arrives in the config file, not the sources)
                    while (
                        _snapshot(src) == snap and cfg_mtime(cfg_m) == cfg_m
                    ):
                        time.sleep(POLL_SECONDS)
                    break
                m = cfg_mtime(cfg_m)
                if m != cfg_m:
                    cfg_m = m
                    print("[dev-reload] config changed → SIGHUP "
                          "(hot reload)", flush=True)
                    proc.send_signal(signal.SIGHUP)
                    continue
                if _snapshot(src) != snap:
                    print("[dev-reload] source changed → restart", flush=True)
                    proc.terminate()
                    try:
                        proc.wait(timeout=5)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                    break
    except KeyboardInterrupt:
        pass
    finally:
        if proc is not None and proc.poll() is None:
            proc.terminate()


if __name__ == "__main__":
    main()
